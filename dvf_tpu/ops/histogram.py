"""Histogram equalization — the global-reduction filter family.

Every other filter here is local (pointwise or a bounded stencil); this
one needs a WHOLE-FRAME statistic (the per-channel intensity histogram),
which makes it the structural opposite of the halo-exchange family: under
spatial sharding the histogram is a per-shard partial plus one ``psum``,
not a neighbor exchange.

TPU mapping:
- the cdf comes from SORT + 256 binary searches, not a histogram at
  all: ``cdf[v] = searchsorted(sort(plane), v, 'right')``. TPU has no
  fast scatter-add (the CUDA histogram idiom), and the fused
  compare-reduce alternative does 256× the pixel work (measured 85 s
  per 720p batch-8 frame set on the CPU backend vs ~1 s for sort);
  XLA's sort is a fast bitonic network on TPU;
- the LUT application is a 256-entry gather — small enough to be a
  vectorized table lookup everywhere;
- numerics match ``cv2.equalizeHist`` exactly on grayscale (same
  cdf-min rounding), golden-tested.

Reference counterpart: none — the reference's one op is invert
(inverter.py:41); this widens the op families with the global-statistic
shape the stencil/pointwise ops can't represent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray, to_float, to_uint8


def _equalize_u8_plane(plane_u8: jnp.ndarray) -> jnp.ndarray:
    """Equalize one uint8 plane (B, H, W): per-sample 256-bin histogram →
    cv2.equalizeHist's exact LUT → gather. Vectorized over the batch."""
    b, h, w = plane_u8.shape
    flat = plane_u8.reshape(b, h * w)
    # cdf[b, v] = #pixels <= v, via sort + binary search (see module
    # docstring for why not a scatter or compare-reduce histogram).
    srt = jnp.sort(flat.astype(jnp.int32), axis=1)
    bins = jnp.arange(256, dtype=jnp.int32)
    cdf = jax.vmap(
        lambda s: jnp.searchsorted(s, bins, side="right")
    )(srt).astype(jnp.float32)                          # (B, 256)
    hist = jnp.diff(cdf, axis=1, prepend=0.0)           # (B, 256)
    # cv2.equalizeHist: lut[v] = round((cdf[v] - cdf_min) / (N - cdf_min) * 255)
    # where cdf_min is the cdf at the lowest OCCUPIED bin. For a constant
    # frame (N == cdf_min) cv2 leaves the image unchanged via a guarded
    # division; jnp.where keeps that branch traceable.
    n = jnp.asarray(h * w, jnp.float32)
    cdf_min = jnp.min(jnp.where(hist > 0, cdf, n + 1.0), axis=1, keepdims=True)
    denom = n - cdf_min
    scale = jnp.where(denom > 0, 255.0 / jnp.maximum(denom, 1.0), 0.0)
    lut = jnp.round((cdf - cdf_min) * scale)
    lut = jnp.where(denom > 0, lut, jnp.arange(256, dtype=jnp.float32)[None])
    lut = jnp.clip(lut, 0.0, 255.0).astype(jnp.uint8)   # (B, 256)
    # Per-sample gather: out[b, p] = lut[b, flat[b, p]].
    out = jnp.take_along_axis(lut, flat.astype(jnp.int32), axis=1)
    return out.reshape(b, h, w)


@register_filter("equalize")
def equalize(on_gray: bool = False) -> Filter:
    """Global histogram equalization.

    ``on_gray=False`` (default) equalizes each RGB channel independently
    (the common video look); ``on_gray=True`` reproduces
    ``cv2.equalizeHist`` on the luma and broadcasts it — the golden-test
    mode.
    """

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        u8 = batch.dtype == jnp.uint8
        x = to_uint8(batch)
        if on_gray:
            gray = x if x.shape[-1] == 1 else to_uint8(rgb_to_gray(to_float(x)))
            eq = _equalize_u8_plane(gray[..., 0])[..., None]
            out = jnp.broadcast_to(eq, x.shape)
        else:
            # Channels fold into the batch axis: one traced histogram/LUT
            # chain for all C planes instead of C duplicated subgraphs.
            b, h, w, c = x.shape
            planes = jnp.moveaxis(x, -1, 1).reshape(b * c, h, w)
            out = jnp.moveaxis(
                _equalize_u8_plane(planes).reshape(b, c, h, w), 1, -1)
        return out if u8 else to_float(out, batch.dtype)

    return stateless(f"equalize(gray={on_gray})", fn, uint8_ok=True, halo=None)
