"""Histogram equalization — the global-reduction filter family.

Every other filter here is local (pointwise or a bounded stencil); this
one needs a WHOLE-FRAME statistic (the per-channel intensity histogram),
which makes it the structural opposite of the halo-exchange family: under
spatial sharding the histogram is a per-shard partial plus one ``psum``,
not a neighbor exchange.

TPU mapping:
- the cdf comes from SORT + 256 binary searches, not a histogram at
  all: ``cdf[v] = searchsorted(sort(plane), v, 'right')``. TPU has no
  fast scatter-add (the CUDA histogram idiom), and the fused
  compare-reduce alternative does 256× the pixel work (measured 85 s
  per 720p batch-8 frame set on the CPU backend vs ~1 s for sort);
  XLA's sort is a fast bitonic network on TPU;
- the LUT application is a 256-entry gather — small enough to be a
  vectorized table lookup everywhere;
- numerics match ``cv2.equalizeHist`` exactly on grayscale (same
  cdf-min rounding), golden-tested.

Reference counterpart: none — the reference's one op is invert
(inverter.py:41); this widens the op families with the global-statistic
shape the stencil/pointwise ops can't represent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray, to_float, to_uint8


def _plane_cdf(flat_i32: jnp.ndarray) -> jnp.ndarray:
    """(B, P) int32 pixels → (B, 256) float32 cdf: cdf[b, v] = #pixels<=v,
    via sort + binary search (see module docstring for why not a scatter
    or compare-reduce histogram). Under spatial sharding this runs on the
    LOCAL pixels; counts are additive, so one psum makes the global cdf."""
    srt = jnp.sort(flat_i32, axis=1)
    bins = jnp.arange(256, dtype=jnp.int32)
    return jax.vmap(
        lambda s: jnp.searchsorted(s, bins, side="right")
    )(srt).astype(jnp.float32)


def _lut_apply(cdf: jnp.ndarray, flat_i32: jnp.ndarray, n: float) -> jnp.ndarray:
    """cv2.equalizeHist's exact LUT from a (B, 256) cdf over ``n`` total
    pixels, gathered back onto (B, P) pixels → uint8."""
    hist = jnp.diff(cdf, axis=1, prepend=0.0)
    # lut[v] = round((cdf[v] - cdf_min) / (N - cdf_min) * 255), cdf_min =
    # cdf at the lowest OCCUPIED bin. For a constant frame (N == cdf_min)
    # cv2 leaves the image unchanged via a guarded division; jnp.where
    # keeps that branch traceable.
    n = jnp.asarray(n, jnp.float32)
    cdf_min = jnp.min(jnp.where(hist > 0, cdf, n + 1.0), axis=1, keepdims=True)
    denom = n - cdf_min
    scale = jnp.where(denom > 0, 255.0 / jnp.maximum(denom, 1.0), 0.0)
    lut = jnp.round((cdf - cdf_min) * scale)
    lut = jnp.where(denom > 0, lut, jnp.arange(256, dtype=jnp.float32)[None])
    lut = jnp.clip(lut, 0.0, 255.0).astype(jnp.uint8)   # (B, 256)
    return jnp.take_along_axis(lut, flat_i32, axis=1)


def _equalize_u8_plane(plane_u8: jnp.ndarray, reduce_cdf=None,
                       n_total=None) -> jnp.ndarray:
    """Equalize uint8 planes (B, H, W), vectorized over the batch.

    ``reduce_cdf``/``n_total``: the spatial-sharding hooks — inside a
    shard_map, ``reduce_cdf`` is ``psum over 'space'`` and ``n_total``
    the GLOBAL pixel count, so each shard LUTs its rows against the
    whole-frame statistic."""
    b, h, w = plane_u8.shape
    flat = plane_u8.reshape(b, h * w).astype(jnp.int32)
    cdf = _plane_cdf(flat)
    if reduce_cdf is not None:
        cdf = reduce_cdf(cdf)
    out = _lut_apply(cdf, flat, n_total if n_total is not None else h * w)
    return out.reshape(b, h, w)


@register_filter("equalize")
def equalize(on_gray: bool = False) -> Filter:
    """Global histogram equalization.

    ``on_gray=False`` (default) equalizes each RGB channel independently
    (the common video look); ``on_gray=True`` reproduces
    ``cv2.equalizeHist`` on the luma and broadcasts it — the golden-test
    mode.
    """

    def body(batch: jnp.ndarray, reduce_cdf=None, h_total=None) -> jnp.ndarray:
        u8 = batch.dtype == jnp.uint8
        x = to_uint8(batch)
        nt = None if h_total is None else h_total * x.shape[2]
        if on_gray:
            gray = x if x.shape[-1] == 1 else to_uint8(rgb_to_gray(to_float(x)))
            eq = _equalize_u8_plane(gray[..., 0], reduce_cdf, nt)[..., None]
            out = jnp.broadcast_to(eq, x.shape)
        else:
            # Channels fold into the batch axis: one traced histogram/LUT
            # chain for all C planes instead of C duplicated subgraphs.
            b, h, w, c = x.shape
            planes = jnp.moveaxis(x, -1, 1).reshape(b * c, h, w)
            out = jnp.moveaxis(
                _equalize_u8_plane(planes, reduce_cdf, nt).reshape(b, c, h, w),
                1, -1)
        return out if u8 else to_float(out, batch.dtype)

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return body(batch)

    def specialize(mesh, batch_shape):
        """Spatial sharding the global-reduction way: each shard computes
        the cdf of its H-slice (counts are additive) and ONE psum over
        'space' makes the whole-frame statistic — no halo, no gather of
        pixels, 256 floats of collective traffic per plane."""
        from jax.sharding import PartitionSpec as P

        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        d, sp = axes.get("data", 1), axes.get("space", 1)
        b, h = batch_shape[0], batch_shape[1]
        if sp <= 1 or h % sp != 0:
            return None  # engine default: replicate H (correct, just unsharded)
        # H-sharding only needs h % space == 0; an indivisible batch just
        # degrades the batch axis (like ops.style / ops.sr do).
        bspec = "data" if b % d == 0 else None
        spec = P(bspec, "space", None, None)

        def inner(x_shard):
            return body(x_shard,
                        reduce_cdf=lambda cdf: jax.lax.psum(cdf, "space"),
                        h_total=h)

        def sharded_fn(batch, state):
            out = jax.shard_map(
                inner, mesh=mesh,
                in_specs=spec,
                out_specs=spec,
                check_vma=False,
            )(batch)
            return out, state

        return Filter(
            name=f"space(equalize(gray={on_gray}))",
            fn=sharded_fn,
            uint8_ok=True,
            # halo=0: this body OWNS its spatial distribution (the psum);
            # the engine must keep H GSPMD-sharded and must not route it
            # through the stencil halo machinery or replicate H.
            halo=0,
        )

    return stateless(f"equalize(gray={on_gray})", fn, uint8_ok=True, halo=None,
                     specialize=specialize)
