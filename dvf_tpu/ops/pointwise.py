"""Pointwise filters. The reference's one concrete op lives here.

``invert`` is the TPU-native counterpart of ``InverterWorker.__call__``'s
``cv2.bitwise_not`` (inverter.py:41): for uint8, bitwise NOT == ``255 - x``,
which we run directly on uint8 batches — one VPU pass, no float round trip,
half the HBM traffic of a float path. The decode/encode surrounding the
reference op (inverter.py:32,44) is host-side codec work owned by
:mod:`dvf_tpu.transport`, not the filter.
"""

from __future__ import annotations

import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray


@register_filter("invert")
def invert() -> Filter:
    """Color invert - the reference's one op (cv2.bitwise_not, inverter.py:41)."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        if batch.dtype == jnp.uint8:
            # uint8 arithmetic wraps, so 255 - x is exactly bitwise_not.
            return jnp.asarray(255, dtype=jnp.uint8) - batch
        return 1.0 - batch

    return stateless("invert", fn, uint8_ok=True, halo=0)


@register_filter("identity")
def identity() -> Filter:
    """Pass-through — the null filter, useful to measure pipeline overhead
    (the reference measures this implicitly with ``--delay 0``)."""
    return stateless("identity", lambda batch: batch, uint8_ok=True, halo=0)


@register_filter("grayscale")
def grayscale() -> Filter:
    """Rec.601 luma, broadcast back to 3 channels."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        gray = rgb_to_gray(batch, keepdims=True)
        return jnp.broadcast_to(gray, batch.shape)

    return stateless("grayscale", fn, halo=0)


@register_filter("brightness_contrast")
def brightness_contrast(alpha: float = 1.0, beta: float = 0.0) -> Filter:
    """out = alpha * x + beta (x in [0,1])."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.clip(alpha * batch + beta, 0.0, 1.0)

    return stateless(f"brightness_contrast(a={alpha},b={beta})", fn, halo=0)


@register_filter("gamma")
def gamma(g: float = 2.2) -> Filter:
    """Gamma correction: out = x ** (1/g)."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.power(jnp.clip(batch, 0.0, 1.0), g)

    return stateless(f"gamma({g})", fn, halo=0)


@register_filter("threshold")
def threshold(t: float = 0.5) -> Filter:
    """Binary threshold on luma at t."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.where(batch > t, 1.0, 0.0).astype(batch.dtype)

    return stateless(f"threshold({t})", fn, halo=0)


@register_filter("sepia")
def sepia() -> Filter:
    """Classic sepia tone matrix."""
    # Classic sepia matrix, rows = output RGB.
    m = jnp.array(
        [[0.393, 0.769, 0.189],
         [0.349, 0.686, 0.168],
         [0.272, 0.534, 0.131]],
        dtype=jnp.float32,
    )

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        out = jnp.einsum("...c,oc->...o", batch, m.astype(batch.dtype))
        return jnp.clip(out, 0.0, 1.0)

    return stateless("sepia", fn, halo=0)


@register_filter("posterize")
def posterize(levels: int = 4) -> Filter:
    """Quantize each channel to ``levels`` evenly-spaced values."""
    if levels < 2:
        raise ValueError("levels must be >= 2")

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        n = float(levels - 1)
        return jnp.round(jnp.clip(batch, 0.0, 1.0) * n) / n

    return stateless(f"posterize({levels})", fn, halo=0)
