"""Pointwise filters. The reference's one concrete op lives here.

``invert`` is the TPU-native counterpart of ``InverterWorker.__call__``'s
``cv2.bitwise_not`` (inverter.py:41): for uint8, bitwise NOT == ``255 - x``,
which we run directly on uint8 batches — one VPU pass, no float round trip,
half the HBM traffic of a float path. The decode/encode surrounding the
reference op (inverter.py:32,44) is host-side codec work owned by
:mod:`dvf_tpu.transport`, not the filter.
"""

from __future__ import annotations

import jax.numpy as jnp

from dvf_tpu.api.filter import Filter, stateless
from dvf_tpu.ops.registry import register_filter
from dvf_tpu.utils.image import rgb_to_gray


@register_filter("invert")
def invert() -> Filter:
    """Color invert - the reference's one op (cv2.bitwise_not, inverter.py:41)."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        if batch.dtype == jnp.uint8:
            # uint8 arithmetic wraps, so 255 - x is exactly bitwise_not.
            return jnp.asarray(255, dtype=jnp.uint8) - batch
        return 1.0 - batch

    return stateless("invert", fn, uint8_ok=True, halo=0)


@register_filter("identity")
def identity() -> Filter:
    """Pass-through — the null filter, useful to measure pipeline overhead
    (the reference measures this implicitly with ``--delay 0``)."""
    return stateless("identity", lambda batch: batch, uint8_ok=True, halo=0)


@register_filter("grayscale")
def grayscale() -> Filter:
    """Rec.601 luma, broadcast back to 3 channels."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        gray = rgb_to_gray(batch, keepdims=True)
        return jnp.broadcast_to(gray, batch.shape)

    return stateless("grayscale", fn, halo=0)


@register_filter("brightness_contrast")
def brightness_contrast(alpha: float = 1.0, beta: float = 0.0) -> Filter:
    """out = alpha * x + beta (x in [0,1])."""

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.clip(alpha * batch + beta, 0.0, 1.0)

    return stateless(f"brightness_contrast(a={alpha},b={beta})", fn, halo=0)


@register_filter("gamma")
def gamma(g: float = 2.2) -> Filter:
    """Gamma correction: out = x ** (1/g)."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.power(jnp.clip(batch, 0.0, 1.0), g)

    return stateless(f"gamma({g})", fn, halo=0)


@register_filter("threshold")
def threshold(t: float = 0.5) -> Filter:
    """Binary threshold on luma at t."""
    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        return jnp.where(batch > t, 1.0, 0.0).astype(batch.dtype)

    return stateless(f"threshold({t})", fn, halo=0)


@register_filter("sepia")
def sepia() -> Filter:
    """Classic sepia tone matrix."""
    # Classic sepia matrix, rows = output RGB.
    m = jnp.array(
        [[0.393, 0.769, 0.189],
         [0.349, 0.686, 0.168],
         [0.272, 0.534, 0.131]],
        dtype=jnp.float32,
    )

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        out = jnp.einsum("...c,oc->...o", batch, m.astype(batch.dtype))
        return jnp.clip(out, 0.0, 1.0)

    return stateless("sepia", fn, halo=0)


@register_filter("posterize")
def posterize(levels: int = 4) -> Filter:
    """Quantize each channel to ``levels`` evenly-spaced values."""
    if levels < 2:
        raise ValueError("levels must be >= 2")

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        n = float(levels - 1)
        return jnp.round(jnp.clip(batch, 0.0, 1.0) * n) / n

    return stateless(f"posterize({levels})", fn, halo=0)


@register_filter("median_blur")
def median_blur(ksize: int = 3) -> Filter:
    """3×3 median filter matching ``cv2.medianBlur`` (salt-and-pepper
    denoise — the classic video-stream cleanup op).

    TPU lowering: the 9 edge-padded shifted views (cv2's median uses
    BORDER_REPLICATE, unlike our reflect-101 stencils) run through a
    19-op median-of-9 min/max sorting network — pure VPU elementwise
    work XLA fuses into one pass, no sort primitive and no data
    movement beyond the shifted slices. Median is order-preserving, so
    the float [0,1] path commutes exactly with the uint8 golden.
    Only ksize=3 is supported: the median-of-25 network for ksize=5 is
    ~5× the ops for a filter cv2 itself restricts to uint8 at that size.

    ``halo=None`` (never spatially sharded): the halo machinery
    substitutes reflect-101 rows at global frame borders
    (parallel/halo.py) — correct for every other stencil here, but
    cv2.medianBlur's border is EDGE-replicate, so a sharded run would
    diverge from the unsharded golden on the outermost rows. The engine
    replicates H instead (correct-first policy); at 19 min/max ops the
    filter has nothing to gain from spatial sharding anyway.
    """
    if ksize != 3:
        raise ValueError(
            f"median_blur supports ksize=3 only (got {ksize}); larger "
            f"medians need a different algorithm (histogram-based) to be "
            f"worth their arithmetic on any backend")

    def fn(batch: jnp.ndarray) -> jnp.ndarray:
        h, w = batch.shape[1], batch.shape[2]
        x = jnp.pad(batch, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="edge")
        v = [x[:, dy:dy + h, dx:dx + w, :]
             for dy in range(3) for dx in range(3)]

        def ex(a, b):
            # compare-exchange: v[a] <- min, v[b] <- max
            v[a], v[b] = jnp.minimum(v[a], v[b]), jnp.maximum(v[a], v[b])

        # Smith's median-of-9 network (19 compare-exchanges); the median
        # lands in v[4].
        ex(1, 2); ex(4, 5); ex(7, 8)
        ex(0, 1); ex(3, 4); ex(6, 7)
        ex(1, 2); ex(4, 5); ex(7, 8)
        ex(0, 3); ex(5, 8); ex(4, 7)
        ex(3, 6); ex(1, 4); ex(2, 5)
        ex(4, 7); ex(4, 2); ex(6, 4)
        ex(4, 2)
        return v[4]

    return stateless("median_blur(k=3)", fn, halo=None)
