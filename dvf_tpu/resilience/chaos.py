"""Deterministic fault-injection plane (the "chaos" half of the tentpole).

The containment and supervision machinery is only trustworthy if it can
be *tested* deterministically — "unplug the TPU and see" is neither. A
:class:`FaultPlan` is a seedable list of rules, each bound to a named
injection **site** that the hot paths expose behind a nil check (zero
overhead unarmed — the sites do ``if chaos is not None``):

=========== ======================================= =====================
site        hook location                           default effect/kind
=========== ======================================= =====================
``decode``  ``TpuZmqWorker._process_batch``         corrupt JPEG bytes →
            (per incoming blob)                     ``decode`` fault
``transport`` ``TpuZmqWorker._run_loop`` (per       truncate the ZMQ
            received multipart message)             multipart → malformed
``h2d``     ``ingest.BatchBuilder._launch`` (per    raise ``h2d``
            shard ``device_put``)                   ChaosFault, or delay
``d2h``     ``egress.ShardedBatchFetcher.fetch``    raise ``d2h``
            (per output-shard host copy)            ChaosFault, or delay
``compute`` ``Engine.submit``/``submit_resident``   raise ``compute``
            (per batch)                             ChaosFault
``oom``     same engine hook, separate site         raise ``oom``
                                                    ChaosFault
``freeze``  pipeline/serve collect loop (per        sleep ``delay`` s —
            iteration)                              wedges the consumer
                                                    so the stall watchdog
                                                    has something real to
                                                    catch
``replica`` fleet health monitor                    declare the replica
            (``fleet.router.FleetFrontend``, per    being checked LOST
            replica per poll tick)                  (process replicas are
                                                    actually killed) →
                                                    ``replica`` fault,
                                                    drain + migrate +
                                                    restart
``corrupt_wire`` audit-stamped transports (ring     flip ONE post-encode
            queue put, worker egress, bridge        bit inside the digest
            egress — per stamped payload)           envelope → the decode
                                                    hop's verify must
                                                    catch it
                                                    (``integrity``)
``corrupt_device`` serve collect (per fetched       perturb one element
            batch)                                  of row 0 of a valid
                                                    output batch → only
                                                    shadow replay can
                                                    catch it
                                                    (``integrity``)
=========== ======================================= =====================

Triggers are event-indexed (``at`` — explicit 0-based event numbers at
the site, or ``every`` — every Nth event), optionally bounded by
``count``; both are exactly reproducible across runs for the per-batch
sites (one event per blob/message/put/submit). Caveat: the ``freeze``
site counts collect-loop *iterations*, including empty queue polls, so
its event indices are machine-timing dependent — use small ``at``
indices (the loop starts polling immediately) or ``delay``-only rules
when reproducibility matters; the ``replica`` site counts health-poll
events the same way — one event per replica per monitor tick, replicas
checked in id order, so a small ``at`` index selects a victim replica
deterministically (``at=0`` = the first replica, first tick). A probabilistic
``p`` trigger exists for soak-style runs (seeded, but only deterministic
when a single thread drives the site). The ``--chaos`` CLI flag parses
the same spec everywhere (serve, worker), so a failure found in a test
can be replayed end-to-end::

    dvf_tpu serve --chaos "compute:at=3,h2d:every=5:count=2" --chaos-seed 7
    dvf_tpu worker --chaos "decode:every=11,transport:p=0.01"
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from dvf_tpu.resilience.faults import FaultError, FaultKind

# What kind a site's injected faults carry unless the rule says otherwise.
# Only sites that are actually wired into a hot path belong here —
# FaultPlan.parse validates against this map, so an unwired name would
# otherwise parse fine and silently inject nothing. (Geometry faults
# have no injection site: inject them for real by switching the JPEG
# stream's dimensions mid-run, as tests/test_resilience.py does.)
SITE_KINDS = {
    "decode": FaultKind.DECODE,
    "transport": FaultKind.TRANSPORT,
    "h2d": FaultKind.H2D,
    "d2h": FaultKind.D2H,
    "compute": FaultKind.COMPUTE,
    "oom": FaultKind.OOM,
    "freeze": FaultKind.STALL,
    "replica": FaultKind.REPLICA,
    # Audit-plane sites (obs.audit): corruption that PARSES — the wire
    # flip lands post-encode inside a digest-stamped envelope; the
    # device flip perturbs one element of an otherwise-valid output
    # batch. Neither raises at injection: detection (or the lack of it)
    # is exactly what the audit acceptance tests measure.
    "corrupt_wire": FaultKind.INTEGRITY,
    "corrupt_device": FaultKind.INTEGRITY,
    # Hot-swap sites (runtime.engine double-buffer): event 0 of a swap
    # is the aside-compile (prepare_swap), event 1 the mid-migrate
    # commit — a rule's ``at=`` indices pick which half fails. Either
    # failure must leave the OLD program serving untouched.
    "swap": FaultKind.COMPUTE,
    # Continuity-plane network sites (resilience.continuity): the delivery
    # path between a session's engine and its client. ``net_partition``
    # raises a ``partition`` ChaosFault at the poll/recv hop — the link
    # goes dark and the reconnect/replay machinery must recover without
    # losing or reordering a frame. The other three never raise: they
    # mutate the delivery stream itself (``dup`` repeats the head,
    # ``reorder`` rotates the window, ``delay`` sleeps), which is exactly
    # the at-least-once noise dedup-by-index must absorb.
    "net_partition": FaultKind.PARTITION,
    "net_dup": FaultKind.TRANSPORT,
    "net_reorder": FaultKind.TRANSPORT,
    "net_delay": FaultKind.TRANSPORT,
}


class ChaosFault(FaultError):
    """An injected fault (subclass so ``classify`` sees the kind)."""


@dataclasses.dataclass
class ChaosRule:
    site: str
    kind: str = ""            # defaults to SITE_KINDS[site]
    every: int = 0            # fire on every Nth event (1-based period)
    at: Tuple[int, ...] = ()  # fire on these 0-based event indices
    p: float = 0.0            # fire with this probability per event
    count: int = -1           # max fires (-1 = unlimited)
    delay_s: float = 0.0      # sleep instead of raising (h2d delay, freeze)
    fired: int = 0

    def __post_init__(self):
        if not self.kind:
            self.kind = SITE_KINDS.get(self.site, FaultKind.INTERNAL)
        if not (self.every or self.at or self.p):
            # A rule with no trigger means "every event" — explicit beats
            # silently-inert.
            self.every = 1

    def wants(self, index: int, rng: random.Random) -> bool:
        if self.count >= 0 and self.fired >= self.count:
            return False
        if self.at:
            return index in self.at
        if self.every:
            return (index + 1) % self.every == 0
        return rng.random() < self.p


class FaultPlan:
    """A seeded set of :class:`ChaosRule` s; one per run, shared by every
    armed component (engine, assembler, worker, pipeline, frontend)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rules: List[ChaosRule] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}

    # -- construction ----------------------------------------------------

    def add(self, site: str, **kw) -> "FaultPlan":
        self.rules.append(ChaosRule(site=site, **kw))
        return self

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the ``--chaos`` CLI grammar: comma-separated rules, each
        ``site[:key=value]*`` with keys ``every``, ``at`` (``/``-separated
        indices), ``p``, ``count``, ``delay``, ``kind``. Example:
        ``"compute:at=3,h2d:every=5:count=2:delay=0.01"``."""
        plan = cls(seed=seed)
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            site = fields[0].strip()
            if site not in SITE_KINDS:
                raise ValueError(
                    f"unknown chaos site {site!r} (valid: "
                    f"{', '.join(sorted(SITE_KINDS))})")
            kw: dict = {}
            for f in fields[1:]:
                k, _, v = f.partition("=")
                k = k.strip()
                if k == "every":
                    kw["every"] = int(v)
                elif k == "at":
                    kw["at"] = tuple(int(x) for x in v.split("/"))
                elif k == "p":
                    kw["p"] = float(v)
                elif k == "count":
                    kw["count"] = int(v)
                elif k == "delay":
                    kw["delay_s"] = float(v)
                elif k == "kind":
                    kw["kind"] = v.strip()
                else:
                    raise ValueError(f"unknown chaos rule key {k!r} in "
                                     f"{part!r}")
            plan.add(site, **kw)
        return plan

    # -- firing ----------------------------------------------------------

    def _match(self, site: str) -> Optional[ChaosRule]:
        """Advance the site's event counter; return the rule that fires
        for this event (first match wins), if any."""
        with self._lock:
            idx = self._counters.get(site, 0)
            self._counters[site] = idx + 1
            for rule in self.rules:
                if rule.site == site and rule.wants(idx, self._rng):
                    rule.fired += 1
                    return rule
        return None

    def fire(self, site: str) -> None:
        """Raise (or delay) if a rule triggers at this site's next event.
        No-op otherwise — hot paths guard with ``if chaos is not None``."""
        rule = self._match(site)
        if rule is None:
            return
        if rule.delay_s > 0:
            time.sleep(rule.delay_s)
            return
        raise ChaosFault(rule.kind,
                         f"chaos[{site}] injected {rule.kind} fault "
                         f"(fire #{rule.fired}, seed {self.seed})")

    def corrupt(self, site: str, blob: bytes) -> bytes:
        """Deterministically mangle a payload (JPEG bytes) when a rule
        triggers: the header survives (so probes still identify a JPEG)
        but the entropy-coded body is truncated and zero-stuffed, which
        every decoder rejects."""
        rule = self._match(site)
        if rule is None:
            return blob
        keep = max(4, len(blob) // 3)
        return blob[:keep] + b"\x00" * 16

    def flip_bit(self, site: str, blob: bytes,
                 protect: int = 12) -> bytes:
        """Flip ONE bit of ``blob`` when a rule triggers — the
        post-encode wire corruption the audit envelope must catch.
        The first ``protect`` bytes (the envelope header: magic,
        version, digest — obs.audit.WIRE_HEADER_LEN) are spared so the
        corrupted payload still PARSES as a stamped frame; flipping the
        magic instead would be caught by the cheaper strict-framing
        check, which is not the failure mode under test. Position is
        deterministic per fire (seeded arithmetic, no clock/rng)."""
        rule = self._match(site)
        if rule is None or len(blob) <= protect:
            return blob
        pos = protect + ((rule.fired * 7919) % (len(blob) - protect))
        out = bytearray(blob)
        out[pos] ^= 0x01
        return bytes(out)

    def perturb(self, site: str) -> bool:
        """Fire-and-report trigger for in-place array corruption sites
        (``corrupt_device``): True when a rule fires this event — the
        caller applies the perturbation (obs.audit.
        maybe_corrupt_device), because the payload is an ndarray the
        plan should not be reshaping itself."""
        return self._match(site) is not None

    def truncate(self, site: str, parts: list) -> list:
        """Drop all but the first frame of a multipart message when a rule
        triggers — the wire-level 'peer sent garbage' fault."""
        rule = self._match(site)
        if rule is None:
            return parts
        return parts[:1]

    def dup(self, site: str, items: list) -> list:
        """Duplicate the head of a delivery list when a rule triggers —
        at-least-once wire noise (``net_dup``). The duplicate is the
        same object; dedup-by-index downstream must drop it, so sharing
        the reference is safe and copy-free."""
        rule = self._match(site)
        if rule is None or not items:
            return items
        return [items[0]] + list(items)

    def reorder(self, site: str, items: list) -> list:
        """Rotate a delivery list one position when a rule triggers
        (head moves to the tail) — deterministic out-of-order arrival
        (``net_reorder``). A single rotation is enough to violate index
        monotonicity, which is what the resequencing path must absorb."""
        rule = self._match(site)
        if rule is None or len(items) < 2:
            return items
        return list(items[1:]) + [items[0]]

    # -- observability ---------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "events": dict(self._counters),
                "fired": {
                    f"{r.site}:{r.kind}": r.fired
                    for r in self.rules if r.fired
                },
            }
