"""Session continuity plane: liveness, reconnect, replay, and resume.

Everything below the serve tier treats a dead peer as an *error*; this
module is the shared vocabulary that turns it into an operating regime.
Four small, independently testable pieces compose into the continuity
guarantees the wire planes and the fleet front door build on:

``LivenessMonitor`` + ``HeartbeatConfig``
    Bounded-timeout last-seen tracking. A peer that has not produced a
    message (or an explicit heartbeat) within ``timeout_s`` is declared
    *partitioned* — a measured, classified event
    (:data:`~dvf_tpu.resilience.faults.FaultKind.PARTITION`), not a
    silent stall. The monitor never does I/O; each wire plane feeds it
    from its own poll loop.

``ReconnectPolicy``
    Seeded jittered exponential backoff for the reconnect that follows a
    partition. Jitter is deterministic per (seed, attempt) so chaos runs
    replay exactly; the cap bounds the worst-case dark window.

``ReplayRing``
    A bounded delivered-tail ring keyed by frame index. Sessions record
    every delivery into their ring; a resuming client replays the tail
    from its last-seen index and dedups by index, which upgrades the
    at-most-once delivery of the base planes to effectively-exactly-once
    *within the replay window*. The ring stores references (frames are
    already owned by the delivery path), so the cost is one dict slot
    per delivered frame.

Resume tokens (:func:`make_resume_token` / :func:`check_resume_token`)
    A keyed-BLAKE2 MAC over ``(session id, epoch)``. ``open_stream``
    hands one out; a reconnecting client (or a front door restarted from
    a snapshot) presents it to prove the resume targets the session it
    was issued for. The secret never leaves the issuing process except
    via the crash snapshot, which is what lets a *restarted* front door
    honor tokens issued by its previous incarnation.

``ResumableStream``
    The client half of exactly-once: tracks submitted source frames,
    absorbs deliveries with dedup-by-index, names the gaps so the caller
    can resubmit them, and reassembles the stream in source order. Under
    ``net_dup``/``net_reorder``/``net_partition`` chaos plus replica
    SIGKILL, ``assembled()`` is byte-identical to a fault-free run —
    that is the invariant ``benchmarks/continuity_bench.py`` soaks.

Crash-consistent state (:func:`atomic_write_json` / :func:`load_json`)
    tmp-file + ``os.replace`` snapshot discipline for the fleet router's
    session registry. A snapshot is either the old document or the new
    one, never a torn write — ``kill -9`` at any instant leaves a
    loadable file.

All counters roll up into :class:`ContinuityStats`, exported as flat
``dvf_continuity_*`` gauges through each owner's ``signals()``.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import hmac
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dvf_tpu.resilience.faults import FaultError, FaultKind


class PartitionError(FaultError):
    """A liveness timeout declared the link dead (kind ``partition``)."""

    def __init__(self, message: str):
        super().__init__(FaultKind.PARTITION, message)


@dataclasses.dataclass
class HeartbeatConfig:
    """Liveness + reconnect tuning shared by the three wire planes.

    ``timeout_s`` must comfortably exceed ``interval_s`` (a single lost
    heartbeat is noise, not a partition); the default 4× ratio follows
    the usual phi-accrual rule of thumb without the machinery."""

    interval_s: float = 0.5      # how often a quiet peer emits a beat
    timeout_s: float = 2.0       # silence beyond this = partitioned
    backoff_base_s: float = 0.05  # first reconnect delay
    backoff_max_s: float = 2.0    # cap on the exponential
    backoff_jitter: float = 0.25  # ±fraction of the delay, seeded
    replay_window: int = 64       # delivered-tail frames kept for resume

    def validate(self) -> "HeartbeatConfig":
        if self.timeout_s <= self.interval_s:
            raise ValueError(
                f"heartbeat timeout_s ({self.timeout_s}) must exceed "
                f"interval_s ({self.interval_s}): one lost beat is not "
                f"a partition")
        return self


class ReconnectPolicy:
    """Jittered exponential backoff, deterministic per (seed, attempt).

    ``next_delay()`` advances the attempt counter and returns the delay
    to sleep before the next connect attempt; ``reset()`` on success.
    Jitter is drawn from a Random seeded once, so a seeded chaos run
    reproduces its exact reconnect timeline."""

    def __init__(self, config: Optional[HeartbeatConfig] = None,
                 seed: int = 0):
        self.config = config or HeartbeatConfig()
        self._rng = random.Random(seed)
        self.attempt = 0
        self.reconnects = 0   # lifetime successful resets

    def next_delay(self) -> float:
        c = self.config
        base = min(c.backoff_max_s,
                   c.backoff_base_s * (2.0 ** self.attempt))
        self.attempt += 1
        if c.backoff_jitter <= 0:
            return base
        # uniform in [1-j, 1+j]; never negative, never zero
        return base * (1.0 + c.backoff_jitter
                       * (2.0 * self._rng.random() - 1.0))

    def reset(self) -> None:
        if self.attempt:
            self.reconnects += 1
        self.attempt = 0


class LivenessMonitor:
    """Last-seen tracking for a set of peers (thread-safe, no I/O).

    Owners call :meth:`beat` on every message (data counts as liveness —
    explicit heartbeats only matter on quiet links) and poll
    :meth:`dead` from their loop to reap partitioned peers."""

    def __init__(self, timeout_s: float = 2.0):
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._last: Dict[Any, float] = {}

    def beat(self, peer: Any, now: Optional[float] = None) -> None:
        with self._lock:
            self._last[peer] = time.monotonic() if now is None else now

    def alive(self, peer: Any, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._last.get(peer)
        return last is not None and (now - last) <= self.timeout_s

    def silence_s(self, peer: Any,
                  now: Optional[float] = None) -> Optional[float]:
        """Seconds since the peer's last beat (None = never seen)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._last.get(peer)
        return None if last is None else max(0.0, now - last)

    def dead(self, now: Optional[float] = None) -> List[Any]:
        """Peers silent beyond the timeout (still tracked until
        :meth:`forget` — the caller owns the reap action)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return [p for p, last in self._last.items()
                    if (now - last) > self.timeout_s]

    def forget(self, peer: Any) -> None:
        with self._lock:
            self._last.pop(peer, None)

    def peers(self) -> List[Any]:
        with self._lock:
            return list(self._last)


class ReplayRing:
    """Bounded delivered-tail ring keyed by frame index (thread-safe).

    ``push`` evicts the oldest entry beyond ``capacity``;
    ``replay_from(index)`` returns every retained entry with
    ``index >= from_index`` in index order — the resume path's tail.
    Indices may arrive out of order (``net_reorder``): the ring keys by
    index, not arrival, so replay order is always correct."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._items: "collections.OrderedDict[int, Any]" = \
            collections.OrderedDict()
        self.pushed = 0
        self.evicted = 0

    def push(self, index: int, item: Any) -> None:
        with self._lock:
            if index in self._items:   # duplicate delivery: keep first
                return
            self._items[index] = item
            self.pushed += 1
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)
                self.evicted += 1

    def replay_from(self, from_index: int) -> List[Tuple[int, Any]]:
        with self._lock:
            return sorted(
                ((i, v) for i, v in self._items.items()
                 if i >= from_index),
                key=lambda pair: pair[0])

    def oldest(self) -> Optional[int]:
        with self._lock:
            return min(self._items) if self._items else None

    def latest(self) -> Optional[int]:
        with self._lock:
            return max(self._items) if self._items else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


# -- resume tokens -------------------------------------------------------

_TOKEN_VERSION = "ct1"


def new_secret() -> bytes:
    """A per-frontend token-signing key (16 random bytes)."""
    return os.urandom(16)


def make_resume_token(session_id: str, epoch: int, secret: bytes) -> str:
    """MAC ``(session_id, epoch)`` under ``secret``.

    The epoch is the issuing incarnation's marker (the fleet uses its
    session generation); it rides in the clear so the verifier can
    recompute the MAC without a lookup. Format:
    ``ct1.<epoch>.<hex mac>`` — session id deliberately NOT embedded
    (the client already names the session it resumes; embedding it
    would only add a parsing surface)."""
    mac = hashlib.blake2b(
        f"{session_id}:{int(epoch)}".encode(), key=secret,
        digest_size=16).hexdigest()
    return f"{_TOKEN_VERSION}.{int(epoch)}.{mac}"


def check_resume_token(token: str, session_id: str,
                       secret: bytes) -> Optional[int]:
    """Verify ``token`` against ``session_id``; return its epoch, or
    None on any mismatch (wrong session, wrong key, malformed, wrong
    version). Constant-time MAC comparison; never raises."""
    try:
        version, epoch_s, mac = str(token).split(".", 2)
        if version != _TOKEN_VERSION:
            return None
        epoch = int(epoch_s)
        want = hashlib.blake2b(
            f"{session_id}:{epoch}".encode(), key=secret,
            digest_size=16).hexdigest()
        return epoch if hmac.compare_digest(mac, want) else None
    except Exception:  # noqa: BLE001 — verification must never raise
        return None


# -- crash-consistent snapshots ------------------------------------------

def atomic_write_json(path: str, doc: dict) -> None:
    """Write ``doc`` so a crash at ANY instant leaves either the old
    snapshot or the new one on disk: serialize to a sibling tmp file,
    fsync it, then ``os.replace`` (atomic within a filesystem)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    data = json.dumps(doc, sort_keys=True).encode()
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


def load_json(path: str) -> Optional[dict]:
    """Load a snapshot; None when missing or unparsable (a torn write
    cannot happen under :func:`atomic_write_json`, but a half-written
    foreign file should degrade to 'no snapshot', not a crash)."""
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode())
        return doc if isinstance(doc, dict) else None
    except Exception:  # noqa: BLE001
        return None


# -- shared counters ------------------------------------------------------

class ContinuityStats:
    """Thread-safe counters for the continuity plane, exported as flat
    ``dvf_continuity_*`` gauges. One instance per owner (bridge, worker,
    gate, fleet front door); the owner merges ``signals()`` into its
    own scrape export."""

    FIELDS = ("partitions", "reconnects", "reconnect_failures",
              "heartbeats", "replays", "replayed_frames", "dup_drops",
              "resumes", "resume_rejected", "snapshots",
              "adopted_replicas", "adopted_sessions")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {f: 0 for f in self.FIELDS}

    def inc(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._counts[field] = self._counts.get(field, 0) + n

    def get(self, field: str) -> int:
        with self._lock:
            return self._counts.get(field, 0)

    def summary(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def signals(self) -> Dict[str, float]:
        with self._lock:
            return {f"dvf_continuity_{k}": float(v)
                    for k, v in self._counts.items()}


# -- client-side exactly-once assembly ------------------------------------

class ResumableStream:
    """The client half of replay-window exactly-once delivery.

    The fleet assigns delivery indices itself (a resubmitted source
    frame gets a NEW index), so naive dedup-by-index alone cannot
    reassemble a stream across retries. This helper keeps the two maps
    that make it work:

    - :meth:`note_submit` records ``delivery index -> source frame n``
      at each submit (including resubmits of a lost frame);
    - :meth:`absorb` dedups incoming deliveries by delivery index
      (``net_dup`` noise and replay overlap both collapse here) and
      slots each surviving frame by its source n;
    - :meth:`missing` names the source frames still undelivered, so the
      caller can resubmit exactly those after a partition or replica
      loss;
    - :meth:`assembled` returns the frames in source order — the thing
      chaos acceptance compares byte-for-byte against a fault-free run.

    Single-client-thread object (matches submit/poll ownership); the
    dedup set is bounded (``seen_capacity``) with FIFO eviction, safe
    because duplicates only ever arrive within the replay window."""

    def __init__(self, seen_capacity: int = 4096):
        self._source_of: Dict[int, int] = {}   # delivery idx -> source n
        self._frames: Dict[int, Any] = {}      # source n -> delivery
        self._seen: set = set()
        self._seen_fifo: "collections.deque[int]" = collections.deque()
        self._seen_capacity = max(16, int(seen_capacity))
        self.submitted = 0
        self.resubmitted = 0
        self.dup_drops = 0
        self.unknown_drops = 0   # delivery index we never submitted

    def note_submit(self, index: int, source_n: int) -> None:
        if source_n in self._frames:
            return   # already delivered: a racing resubmit is moot
        if index in self._source_of:
            return
        prior = source_n in set(self._source_of.values())
        self._source_of[index] = source_n
        self.submitted += 1
        if prior:
            self.resubmitted += 1

    def absorb(self, deliveries: List[Any]) -> List[Tuple[int, Any]]:
        """Fold a poll batch in; returns the NEW ``(source_n,
        delivery)`` pairs in arrival order (duplicates and unknowns
        dropped and counted)."""
        fresh: List[Tuple[int, Any]] = []
        for d in deliveries:
            idx = d.index
            if idx in self._seen:
                self.dup_drops += 1
                continue
            self._seen.add(idx)
            self._seen_fifo.append(idx)
            while len(self._seen_fifo) > self._seen_capacity:
                self._seen.discard(self._seen_fifo.popleft())
            n = self._source_of.pop(idx, None)
            if n is None:
                self.unknown_drops += 1
                continue
            if n in self._frames:
                # an older retry of the same source frame landed first;
                # content is identical (deterministic filter), keep it
                self.dup_drops += 1
                continue
            self._frames[n] = d
            fresh.append((n, d))
        return fresh

    def missing(self, upto_n: int) -> List[int]:
        """Source frames ``0..upto_n-1`` not yet delivered — the exact
        resubmission list after a loss event."""
        return [n for n in range(upto_n) if n not in self._frames]

    def delivered_count(self) -> int:
        return len(self._frames)

    def assembled(self) -> List[Any]:
        """Deliveries in source order (gaps omitted — run
        :meth:`missing` to zero first for the gap-free guarantee)."""
        return [self._frames[n] for n in sorted(self._frames)]
