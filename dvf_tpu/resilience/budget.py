"""Per-kind error budgets: bounded containment with drop → degrade → fail.

The pre-existing containment sites (`Pipeline._contain`,
`ServeFrontend._contain`, the worker's run loop) would swallow faults
*forever* in resilient mode — a permanently-broken engine became a silent
0-fps server that still answered ``stats()``. An :class:`ErrorBudget`
bounds that: each :class:`~dvf_tpu.resilience.faults.FaultKind` gets a
budget of N contained faults inside a sliding window of T seconds, and
overflowing the budget escalates instead of looping:

1. within budget  → ``"contain"`` — drop the frame/batch, count, continue
   (the reference's live-mode semantics, now bounded);
2. first overflow → ``"degrade"`` — the site applies its degradation if it
   has one (streamed→monolithic ingest after repeated ``h2d`` faults,
   engine rebuild after repeated ``compute``/``oom`` faults) and the
   window restarts so the degraded configuration gets a fresh budget;
3. second overflow → ``"fail"`` — the degraded configuration is *also*
   broken; surface a hard error (``ServeError`` / pipeline abort) rather
   than shedding frames forever.

Sites with no degradation for a kind treat ``"degrade"`` as ``"fail"``
(there is nothing left to fall back to).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Optional


class ErrorBudget:
    """Sliding-window fault budget with a per-kind escalation ladder."""

    CONTAIN = "contain"
    DEGRADE = "degrade"
    FAIL = "fail"

    def __init__(self, limit: int = 16, window_s: float = 30.0,
                 limits: Optional[Dict[str, int]] = None):
        if limit < 1:
            raise ValueError("fault budget limit must be >= 1")
        self.limit = limit
        self.window_s = window_s
        self.limits = dict(limits) if limits else {}
        self._lock = threading.Lock()
        self._events: Dict[str, Deque[float]] = collections.defaultdict(
            collections.deque)
        self._level: Dict[str, int] = {}

    def record(self, kind: str, now: Optional[float] = None) -> str:
        """Count one contained fault of ``kind``; returns the action."""
        now = time.monotonic() if now is None else now
        limit = self.limits.get(kind, self.limit)
        with self._lock:
            dq = self._events[kind]
            dq.append(now)
            cutoff = now - self.window_s
            while dq and dq[0] < cutoff:
                dq.popleft()
            if len(dq) <= limit:
                return self.CONTAIN
            # Budget overflowed. Restart the window either way: the caller
            # is about to change something (degrade) or die (fail), and a
            # stale backlog must not instantly re-trip the fresh state.
            dq.clear()
            level = self._level.get(kind, 0)
            self._level[kind] = level + 1
            return self.DEGRADE if level == 0 else self.FAIL

    def level(self, kind: str) -> int:
        """0 = never overflowed, 1 = degraded once, >=2 = failed."""
        with self._lock:
            return self._level.get(kind, 0)

    def summary(self) -> dict:
        with self._lock:
            return {
                "limit": self.limit,
                "window_s": self.window_s,
                "escalations": dict(self._level),
            }


def escalate(budget: ErrorBudget, kind: str, degrade=None) -> str:
    """The shared containment ladder, one step: record a contained fault
    and resolve it to ``CONTAIN`` or ``FAIL``.

    Within budget → ``CONTAIN``. On the first overflow the site's
    ``degrade(kind)`` callback runs; a successful degradation folds back
    to ``CONTAIN`` (the degraded configuration gets the fresh window
    ``record`` started). Everything else → ``FAIL``. Sites whose normal
    containment already *is* the recovery (the worker's geometry
    re-probe, stall recovery) pass ``degrade=lambda kind: True`` so the
    first overflow keeps containing and only the second fails. One
    helper, three callers (pipeline, serve frontend, ZMQ worker) — the
    ladder can't drift between them.
    """
    action = budget.record(kind)
    if action == ErrorBudget.CONTAIN:
        return action
    if action == ErrorBudget.DEGRADE and degrade is not None and degrade(kind):
        return ErrorBudget.CONTAIN
    return ErrorBudget.FAIL
