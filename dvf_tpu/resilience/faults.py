"""Structured fault taxonomy for every containment site in the stack.

The reference pipeline's whole fault story is worker.py:71-76 — "drop one
bad frame and keep going" — and until this module our port mirrored it:
each containment site (`Pipeline._contain`, `ServeFrontend._contain`,
`TpuZmqWorker.run`) swallowed a bare ``Exception`` and bumped one opaque
``errors`` counter. That loses exactly the information an operator (or a
BENCH round asserting "zero unexpected faults") needs: *what class of
thing* failed, how often, and what the last instance looked like.

``FaultKind`` is the shared vocabulary. Every contained error is
classified into one kind, counted per kind in a :class:`FaultStats`
(exported through pipeline/serve/worker ``stats()`` and the bench JSON),
and fed to the per-kind :class:`~dvf_tpu.resilience.budget.ErrorBudget`
that decides drop → degrade → fail escalation.

Classification is two-layered: code that *knows* what failed raises (or
wraps into) a :class:`FaultError` carrying its kind — the streamed-ingest
``device_put`` wraps as ``h2d``, the ZMQ worker's decode wraps as
``decode``, chaos injections carry their configured kind — and everything
else is classified by :func:`classify` from the exception type/message
plus the containment site it surfaced at.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class FaultKind:
    """The error taxonomy (string constants, not an Enum — the values ride
    through JSON stats payloads and log lines as-is)."""

    DECODE = "decode"        # frame/JPEG decode, source read
    GEOMETRY = "geometry"    # stream geometry changed mid-flight (re-probe)
    TRANSPORT = "transport"  # malformed/truncated wire messages, socket
    #                          errors, result encode/send failures (the
    #                          egress codec plane's wire-prep domain)
    H2D = "h2d"              # host→device transfer (device_put) failures
    D2H = "d2h"              # device→host transfer (streamed result fetch)
    COMPUTE = "compute"      # the jitted step / result materialization
    OOM = "oom"              # device memory exhaustion
    STALL = "stall"          # watchdog: in-flight work older than the timeout
    REPLICA = "replica"      # a fleet engine replica was lost (process died,
    #                          RPC channel broke, health check failed) —
    #                          the fleet tier's drain/migrate/restart domain
    INTEGRITY = "integrity"  # the audit plane's domain (obs.audit):
    #                          content-digest mismatch on a framed wire
    #                          payload, a shadow-replay or swap-guard
    #                          divergence from the golden path — the
    #                          pixels are WRONG even though everything
    #                          parsed and delivered
    PARTITION = "partition"  # the continuity plane's domain
    #                          (resilience.continuity): a peer went
    #                          silent past its liveness timeout — the
    #                          link is partitioned, not merely slow.
    #                          Distinct from TRANSPORT (the bytes were
    #                          wrong) and STALL (our own work wedged):
    #                          nothing arrived at all, and the response
    #                          is a budgeted reconnect, not a drop.
    INTERNAL = "internal"    # everything else (bookkeeping bugs, sinks)


ALL_KINDS = (
    FaultKind.DECODE, FaultKind.GEOMETRY, FaultKind.TRANSPORT,
    FaultKind.H2D, FaultKind.D2H, FaultKind.COMPUTE, FaultKind.OOM,
    FaultKind.STALL, FaultKind.REPLICA, FaultKind.INTEGRITY,
    FaultKind.PARTITION, FaultKind.INTERNAL,
)

# Default classification for exceptions that carry no kind of their own,
# keyed by the containment site that caught them (the site string each
# `_contain(e, where)` call already passes).
_SITE_DEFAULT = {
    # single-stream pipeline sites
    "ingest": FaultKind.DECODE,      # source read/decode domain
    "dispatch": FaultKind.COMPUTE,   # staging + engine submit
    "collect": FaultKind.COMPUTE,    # result materialization
    "sink": FaultKind.INTERNAL,
    # zmq worker / serving sites
    "decode": FaultKind.DECODE,
    "transport": FaultKind.TRANSPORT,
    "h2d": FaultKind.H2D,
    "d2h": FaultKind.D2H,
    "encode": FaultKind.TRANSPORT,   # egress codec plane: wire-prep domain
    "compute": FaultKind.COMPUTE,
    "worker": FaultKind.COMPUTE,     # worker loop: engine is the main residue
}

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Resource exhausted")


class FaultError(RuntimeError):
    """An error with a known :class:`FaultKind` attached.

    Raised directly by chaos injections and by containment sites that
    escalate ("error budget exhausted"), and used to wrap exceptions at
    the few points that know exactly which fault domain failed (the
    streamed-ingest ``device_put``, the worker's decode path).
    """

    def __init__(self, kind: str, message: str, fatal: bool = False):
        super().__init__(message)
        self.kind = kind
        self.fatal = fatal  # budget-exhaustion errors set this so generic
        #   per-iteration containment re-raises instead of re-containing


def classify(exc: BaseException, site: Optional[str] = None) -> str:
    """Map one contained exception to its :class:`FaultKind`."""
    if isinstance(exc, FaultError):
        return exc.kind
    try:  # lazy: transport.codec is optional-dependency-adjacent
        from dvf_tpu.transport.codec import JpegGeometryError

        if isinstance(exc, JpegGeometryError):
            return FaultKind.GEOMETRY
    except Exception:  # noqa: BLE001 — classification must never raise
        pass
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in _OOM_MARKERS):
        return FaultKind.OOM
    if isinstance(exc, (TimeoutError,)):
        return FaultKind.STALL
    return _SITE_DEFAULT.get(site or "", FaultKind.INTERNAL)


class FaultStats:
    """Per-kind fault counters + last-error records (thread-safe).

    One instance per pipeline/frontend/worker; ``summary()`` is embedded
    in their ``stats()`` exports and the bench JSON so a BENCH round can
    assert exact per-kind counts (zero, for a clean run).

    ``replica``: the fleet tier runs one frontend (and so one FaultStats)
    per engine replica; labeling the recorder attributes every fault —
    and every fault record — to the replica that absorbed it, so the
    merged fleet export (and a fleet bench round's ``faults`` JSON) can
    say *which* replica ate what instead of anonymous per-kind counters.
    Single-engine paths leave it None and the summary shape is unchanged.
    """

    def __init__(self, replica: Optional[str] = None):
        self._lock = threading.Lock()
        self.replica = replica
        self.counts: Dict[str, int] = {}
        self.last: Dict[str, dict] = {}
        self.by_replica: Dict[str, Dict[str, int]] = {}

    def record(self, kind: str, exc: Optional[BaseException] = None,
               replica: Optional[str] = None) -> None:
        rep = replica if replica is not None else self.replica
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            rec = {
                "error": repr(exc) if exc is not None else None,
                "ts": time.time(),
            }
            if rep is not None:
                rec["replica"] = rep
                per = self.by_replica.setdefault(rep, {})
                per[kind] = per.get(kind, 0) + 1
            self.last[kind] = rec

    def count(self, kind: str) -> int:
        with self._lock:
            return self.counts.get(kind, 0)

    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def summary(self) -> dict:
        with self._lock:
            out = {
                "total": sum(self.counts.values()),
                "by_kind": dict(self.counts),
                "last": {k: dict(v) for k, v in self.last.items()},
            }
            if self.by_replica:
                out["by_replica"] = {r: dict(kinds)
                                     for r, kinds in self.by_replica.items()}
            return out

    def absorb_summary(self, summary: dict,
                       replica: Optional[str] = None) -> None:
        """Fold another recorder's exported ``summary()`` into this one —
        the fleet front door merging per-replica exports that arrived
        over an RPC (the recorder object itself never crosses the
        process boundary). ``replica`` attributes the absorbed counts
        when the source summary carries no ``by_replica`` of its own."""
        by_kind = summary.get("by_kind", {}) or {}
        by_replica = summary.get("by_replica") or (
            {replica: by_kind} if replica is not None and by_kind else {})
        with self._lock:
            for kind, n in by_kind.items():
                self.counts[kind] = self.counts.get(kind, 0) + int(n)
            for rep, kinds in by_replica.items():
                per = self.by_replica.setdefault(rep, {})
                for kind, n in kinds.items():
                    per[kind] = per.get(kind, 0) + int(n)
            for kind, rec in (summary.get("last", {}) or {}).items():
                rec = dict(rec)
                if replica is not None and "replica" not in rec:
                    rec["replica"] = replica
                mine = self.last.get(kind)
                if mine is None or (rec.get("ts") or 0) >= (mine.get("ts") or 0):
                    self.last[kind] = rec
