"""Structured fault taxonomy for every containment site in the stack.

The reference pipeline's whole fault story is worker.py:71-76 — "drop one
bad frame and keep going" — and until this module our port mirrored it:
each containment site (`Pipeline._contain`, `ServeFrontend._contain`,
`TpuZmqWorker.run`) swallowed a bare ``Exception`` and bumped one opaque
``errors`` counter. That loses exactly the information an operator (or a
BENCH round asserting "zero unexpected faults") needs: *what class of
thing* failed, how often, and what the last instance looked like.

``FaultKind`` is the shared vocabulary. Every contained error is
classified into one kind, counted per kind in a :class:`FaultStats`
(exported through pipeline/serve/worker ``stats()`` and the bench JSON),
and fed to the per-kind :class:`~dvf_tpu.resilience.budget.ErrorBudget`
that decides drop → degrade → fail escalation.

Classification is two-layered: code that *knows* what failed raises (or
wraps into) a :class:`FaultError` carrying its kind — the streamed-ingest
``device_put`` wraps as ``h2d``, the ZMQ worker's decode wraps as
``decode``, chaos injections carry their configured kind — and everything
else is classified by :func:`classify` from the exception type/message
plus the containment site it surfaced at.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class FaultKind:
    """The error taxonomy (string constants, not an Enum — the values ride
    through JSON stats payloads and log lines as-is)."""

    DECODE = "decode"        # frame/JPEG decode, source read
    GEOMETRY = "geometry"    # stream geometry changed mid-flight (re-probe)
    TRANSPORT = "transport"  # malformed/truncated wire messages, socket
    #                          errors, result encode/send failures (the
    #                          egress codec plane's wire-prep domain)
    H2D = "h2d"              # host→device transfer (device_put) failures
    D2H = "d2h"              # device→host transfer (streamed result fetch)
    COMPUTE = "compute"      # the jitted step / result materialization
    OOM = "oom"              # device memory exhaustion
    STALL = "stall"          # watchdog: in-flight work older than the timeout
    INTERNAL = "internal"    # everything else (bookkeeping bugs, sinks)


ALL_KINDS = (
    FaultKind.DECODE, FaultKind.GEOMETRY, FaultKind.TRANSPORT,
    FaultKind.H2D, FaultKind.D2H, FaultKind.COMPUTE, FaultKind.OOM,
    FaultKind.STALL, FaultKind.INTERNAL,
)

# Default classification for exceptions that carry no kind of their own,
# keyed by the containment site that caught them (the site string each
# `_contain(e, where)` call already passes).
_SITE_DEFAULT = {
    # single-stream pipeline sites
    "ingest": FaultKind.DECODE,      # source read/decode domain
    "dispatch": FaultKind.COMPUTE,   # staging + engine submit
    "collect": FaultKind.COMPUTE,    # result materialization
    "sink": FaultKind.INTERNAL,
    # zmq worker / serving sites
    "decode": FaultKind.DECODE,
    "transport": FaultKind.TRANSPORT,
    "h2d": FaultKind.H2D,
    "d2h": FaultKind.D2H,
    "encode": FaultKind.TRANSPORT,   # egress codec plane: wire-prep domain
    "compute": FaultKind.COMPUTE,
    "worker": FaultKind.COMPUTE,     # worker loop: engine is the main residue
}

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Resource exhausted")


class FaultError(RuntimeError):
    """An error with a known :class:`FaultKind` attached.

    Raised directly by chaos injections and by containment sites that
    escalate ("error budget exhausted"), and used to wrap exceptions at
    the few points that know exactly which fault domain failed (the
    streamed-ingest ``device_put``, the worker's decode path).
    """

    def __init__(self, kind: str, message: str, fatal: bool = False):
        super().__init__(message)
        self.kind = kind
        self.fatal = fatal  # budget-exhaustion errors set this so generic
        #   per-iteration containment re-raises instead of re-containing


def classify(exc: BaseException, site: Optional[str] = None) -> str:
    """Map one contained exception to its :class:`FaultKind`."""
    if isinstance(exc, FaultError):
        return exc.kind
    try:  # lazy: transport.codec is optional-dependency-adjacent
        from dvf_tpu.transport.codec import JpegGeometryError

        if isinstance(exc, JpegGeometryError):
            return FaultKind.GEOMETRY
    except Exception:  # noqa: BLE001 — classification must never raise
        pass
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in _OOM_MARKERS):
        return FaultKind.OOM
    if isinstance(exc, (TimeoutError,)):
        return FaultKind.STALL
    return _SITE_DEFAULT.get(site or "", FaultKind.INTERNAL)


class FaultStats:
    """Per-kind fault counters + last-error records (thread-safe).

    One instance per pipeline/frontend/worker; ``summary()`` is embedded
    in their ``stats()`` exports and the bench JSON so a BENCH round can
    assert exact per-kind counts (zero, for a clean run).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {}
        self.last: Dict[str, dict] = {}

    def record(self, kind: str, exc: Optional[BaseException] = None) -> None:
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.last[kind] = {
                "error": repr(exc) if exc is not None else None,
                "ts": time.time(),
            }

    def count(self, kind: str) -> int:
        with self._lock:
            return self.counts.get(kind, 0)

    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def summary(self) -> dict:
        with self._lock:
            return {
                "total": sum(self.counts.values()),
                "by_kind": dict(self.counts),
                "last": {k: dict(v) for k, v in self.last.items()},
            }
