"""Fault taxonomy, deterministic fault injection, error budgets, and the
stall watchdog / supervised recovery — the shared fault model every
containment site in the stack (pipeline, serving frontend, ZMQ worker)
classifies into and escalates through. See the module docstrings for the
design: faults (taxonomy), chaos (injection plane), budget (drop →
degrade → fail), supervisor (watchdog + recovery).
"""

from dvf_tpu.resilience.budget import ErrorBudget, escalate
from dvf_tpu.resilience.chaos import ChaosFault, ChaosRule, FaultPlan
from dvf_tpu.resilience.faults import (
    ALL_KINDS,
    FaultError,
    FaultKind,
    FaultStats,
    classify,
)
from dvf_tpu.resilience.supervisor import InflightWindow, Supervisor

__all__ = [
    "ALL_KINDS",
    "ChaosFault",
    "ChaosRule",
    "ErrorBudget",
    "FaultError",
    "FaultKind",
    "FaultPlan",
    "FaultStats",
    "InflightWindow",
    "Supervisor",
    "classify",
    "escalate",
]
