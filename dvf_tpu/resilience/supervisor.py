"""Stall watchdog + supervised recovery for the serving runtimes.

The containment sites catch errors that *raise*. They are blind to the
silent failure modes: a device batch whose result never materializes, a
collect thread wedged mid-``np.asarray``, an engine thread that died
without setting the stop flag. GPUOS (arXiv 2604.17861) frames the fix:
treat the device runtime as a supervised, OS-like resource — watch it,
and when it wedges, *recover* it instead of trusting it.

Two pieces:

:class:`InflightWindow`
    Lock-protected registry of submitted-but-uncollected batches, keyed
    by dispatch sequence number, each carrying its submit time (monotonic
    clock) and an opaque payload (the serve path stores the
    ``BatchPlan`` so a recovery can shed its sessions' claims). The age
    of the *oldest* entry is the watchdog signal: a batch older than
    ``stall_timeout_s`` means the collect side stopped making progress —
    whether it is blocked on a hung device, a frozen thread, or a dead
    one.

:class:`Supervisor`
    A daemon thread polling the window age and registered thread
    heartbeats. On a stall it invokes the owner's ``on_stall`` callback
    *synchronously* (the callback performs recovery: shed the window,
    rebuild the engine, replace wedged consumers) and only resumes
    watching when the callback returns, so one stall produces one
    recovery, not a storm. Heartbeat ages are exported for stats;
    recovery decisions key off the window (heartbeats alone false-positive
    on long first-batch compiles).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class InflightWindow:
    """Submitted-but-uncollected batches, oldest-age queryable."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, Tuple[float, Any]] = {}

    def add(self, key: int, payload: Any = None) -> None:
        with self._lock:
            self._entries[key] = (time.monotonic(), payload)

    def remove(self, key: int) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def oldest_age(self, now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._entries:
                return None
            return now - min(t for t, _ in self._entries.values())

    def drain(self) -> List[Tuple[int, Any]]:
        """Atomically empty the window; returns ``(key, payload)`` pairs
        (recovery sheds these — their results are written off)."""
        with self._lock:
            out = [(k, p) for k, (_, p) in self._entries.items()]
            self._entries.clear()
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class Supervisor:
    """Watchdog thread over an :class:`InflightWindow` + thread heartbeats.

    ``on_stall(reason)`` runs in the supervisor thread; it must be safe to
    call concurrently with the supervised threads (the serve/pipeline
    recovery procedures are written for exactly that).
    """

    def __init__(
        self,
        stall_timeout_s: float,
        on_stall: Callable[[str], None],
        poll_s: Optional[float] = None,
        name: str = "dvf-supervisor",
        window: Optional[InflightWindow] = None,
        on_trip: Optional[Callable[[str], None]] = None,
    ):
        if stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be > 0")
        self.stall_timeout_s = stall_timeout_s
        self.on_stall = on_stall
        # Observability tap, fired BEFORE on_stall so it sees the wedged
        # state recovery is about to tear down (the serve frontend hangs
        # its flight-recorder dump here). Best-effort: its failure must
        # neither block nor abort the recovery itself.
        self.on_trip = on_trip
        self.poll_s = poll_s if poll_s is not None else min(
            0.25, stall_timeout_s / 4.0)
        self.name = name
        # The owner may share its own window (the serve frontend tracks
        # in-flight batches even with the watchdog off, so budget-driven
        # recovery can still shed them) — else the supervisor owns one.
        self.window = window if window is not None else InflightWindow()
        self.stalls = 0
        self._beats: Dict[str, float] = {}
        self._beats_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration ----------------------------------------------------

    def beat(self, name: str) -> None:
        """Record liveness for one supervised loop (call every iteration
        — cheap: one dict store under a lock)."""
        with self._beats_lock:
            self._beats[name] = time.monotonic()

    def heartbeat_ages(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._beats_lock:
            return {k: round(now - t, 3) for k, t in self._beats.items()}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Supervisor":
        if self._thread is not None:
            raise RuntimeError("supervisor already started")
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # -- watchdog --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            age = self.window.oldest_age()
            if age is not None and age > self.stall_timeout_s:
                self._trip(f"in-flight batch stalled {age:.2f}s "
                           f"(> {self.stall_timeout_s}s)")

    def _trip(self, reason: str) -> None:
        self.stalls += 1
        if self.on_trip is not None:
            try:
                self.on_trip(reason)
            except Exception as e:  # noqa: BLE001 — a broken observer
                import sys             # must never block recovery

                print(f"[supervisor] on_trip raised (ignored): {e!r}",
                      file=sys.stderr, flush=True)
        try:
            self.on_stall(reason)
        except Exception as e:  # noqa: BLE001 — a failed recovery must not
            # kill the watchdog; the next poll re-trips (and the owner's
            # error budget escalates to a hard fail).
            import sys

            print(f"[supervisor] recovery raised (will re-trip): {e!r}",
                  file=sys.stderr, flush=True)
