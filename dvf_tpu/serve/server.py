"""The serving front door: N client streams, one device, M signatures.

``ServeFrontend`` multiplexes independent client sessions onto a small
pool of compiled programs — the genuinely multi-tenant execution path in
the framework. Sessions group into **signature buckets** keyed by the
canonical ``(op_chain, geometry, dtype)`` triple
(runtime.signature.SignatureKey); each bucket leases its compiled
``Engine`` from a bounded LRU ``ProgramPool``, so a real traffic mix
(mixed filters, resolutions, dtypes) time-shares ONE device instead of
being refused at the door or forked into N processes. Topology (one
process, two service threads around the async device queue, mirroring
the single-stream pipeline's shape):

  clients ──submit──► per-session ingress (drop-oldest)
                          │ dispatch thread: pick ONE bucket per tick
                          ▼ (EDF-headroom ÷ measured tick cost), then
                      ContinuousBatcher EDF within it → one batch
                      bucket.Engine.submit  (in-flight depth bounded
                          │  across buckets — one device queue)
                          │ collect thread: materialize via the
                          ▼ bucket's fetcher → ResultRouter
                      per-session reorder → out queue / sink ──poll──► clients

Admission control is three-layered: ``max_sessions`` caps tenants at
``open_stream`` (AdmissionError beyond), ``max_buckets`` caps live
signatures (a new signature admits by creating a bucket — compiled
AHEAD of its first frame, so the JIT stall happens at admission where
the persistent compilation cache and the program pool turn it into
milliseconds, never on the serving path; beyond the cap the refusal
enumerates the warm signatures this frontend can serve cheaply), and
``max_inflight`` caps device batches in flight (bounding queueing delay
for everyone — the per-batch analog of the single-stream pipeline's
semaphore). Overload beyond that is absorbed by the per-session
drop-oldest bounds and the batcher's SLO shedding, never by blocking a
client.

Only stateless filters are served: a stateful filter's temporal state
would thread *across* batches whose rows belong to different tenants —
cross-session state leakage by construction — so the frontend refuses
them at build time.

``ZmqStreamBridge`` binds one session to the reference app's socket pair
using the exact READY-credit framing of ``transport.zmq_ingress`` — a
reference-style client connects and sees one fast worker, while its
frames share device batches with every other tenant.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dvf_tpu.api.filter import Filter, FilterChain
from dvf_tpu.obs.audit import (
    AuditPlane,
    attach_audit_provider,
    maybe_corrupt_device,
)
from dvf_tpu.obs.export import FlightRecorder, attach_signal_provider
from dvf_tpu.obs import ledger as ledger_mod
from dvf_tpu.obs.ledger import ReconfigLedger
from dvf_tpu.obs.lineage import (
    AttributionPlane,
    load_stage_profile,
    save_stage_profile,
)
from dvf_tpu.obs.memory import (
    LeakTrendWatch,
    attach_memory_provider,
)
from dvf_tpu.obs.metrics import EgressStats, IngestStats, LatencyStats
from dvf_tpu.obs.registry import (
    COUNTER,
    GAUGE,
    MetricSample,
    MetricsRegistry,
    TimeSeriesRing,
)
from dvf_tpu.obs.trace import Tracer
from dvf_tpu.resilience.budget import ErrorBudget, escalate
from dvf_tpu.resilience.continuity import (
    ContinuityStats, HeartbeatConfig, ReconnectPolicy, check_resume_token,
    make_resume_token, new_secret,
)
from dvf_tpu.resilience.faults import FaultError, FaultKind, FaultStats, classify
from dvf_tpu.resilience.supervisor import InflightWindow, Supervisor
from dvf_tpu.runtime.egress import (
    EGRESS_MODES,
    AsyncCodecPlane,
    ShardedBatchFetcher,
)
from dvf_tpu.runtime.engine import Engine, ProgramPool
from dvf_tpu.runtime.ingest import INGEST_MODES, ShardedBatchAssembler
from dvf_tpu.runtime.signature import (
    SignatureKey,
    build_filter,
    canonical_dtype,
    canonical_geometry,
    canonical_op_chain,
    canonical_op_chain_or_verbatim,
    make_key,
    parse_manifest,
)
from dvf_tpu.serve.batcher import BatchPlan, ContinuousBatcher
from dvf_tpu.serve.router import ResultRouter
from dvf_tpu.serve.session import (
    CLOSED,
    OPEN,
    AdmissionError,
    ServeError,
    SessionConfig,
    StreamSession,
)

# Trace track ids (one lane per stage, the pipeline's convention):
# dispatch staging, device span, per-shard H2D / D2H transfer lanes.
# The reconfiguration ledger stamps its events on its own lane
# (obs.ledger.TRACK_LEDGER = 6), clear of all of these.
TRACK_DISPATCH, TRACK_DEVICE, TRACK_H2D, TRACK_D2H = 0, 1, 3, 4

# dvf_compile_ms histogram bounds: serving compiles span sub-ms pool
# hits through multi-second cold XLA runs.
COMPILE_MS_BOUNDS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                     1000.0, 2500.0, 5000.0, 10000.0)

# dvf_swap_stall_ms histogram bounds: a hot swap's serving cost is the
# tick-boundary commit (a pointer swing + optional device-to-device
# state migration) — sub-millisecond to a few ms; anything in the
# hundreds means the compile leaked back onto the dispatch thread.
SWAP_STALL_MS_BOUNDS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                        25.0, 50.0, 100.0, 250.0)


@dataclasses.dataclass
class ServeConfig:
    batch_size: int = 8
    max_sessions: int = 16        # admission cap (open_stream)
    max_buckets: int = 4          # live signature buckets — how many
    #   distinct (op_chain, geometry, dtype) mixes this frontend serves
    #   concurrently; a new signature beyond the cap first retires an
    #   IDLE bucket (no sessions, nothing in flight — its program stays
    #   warm in the pool), else refuses with the warm-signature list
    pool_capacity: int = 8        # compiled-program pool bound (LRU;
    #   ≥ max_buckets keeps every retired bucket's program warm until
    #   genuine capacity pressure — eviction frees device buffers and a
    #   re-admission recompiles through the persistent cache)
    max_inflight: int = 4         # device batches in flight (latency bound)
    queue_size: int = 10          # per-session ingress bound
    slo_ms: float = 1000.0        # default per-stream latency budget
    frame_delay: int = 0          # per-session reorder cursor lag
    reorder_capacity: int = 50
    out_queue_size: int = 64      # per-session poll-side bound
    replay_window: int = 64       # per-session delivered-tail replay ring
    #   (resilience.continuity): resume_stream replays the retained tail
    #   from the client's last-seen index — effectively-exactly-once
    #   delivery within the window. 0 disables (no frames pinned).
    max_retired: int = 64         # closed sessions kept poll-able; oldest
    #   evicted beyond this (a churning long-lived server must not pin
    #   every dead tenant's tail frames forever — release() drops one
    #   explicitly once its client has drained)
    tick_s: float = 0.002         # dispatch idle poll
    resilient: bool = True        # one bad batch is dropped + counted;
    #   serving keeps going (live-mode semantics, like Pipeline.resilient)
    fault_budget: int = 16        # contained faults per kind inside
    #   fault_window_s before escalation (resilience.budget): first
    #   overflow degrades (h2d → monolithic ingest; compute/oom →
    #   supervised engine rebuild), second surfaces a hard ServeError —
    #   a permanently broken engine must not become a silent 0-fps server
    fault_window_s: float = 30.0
    stall_timeout_s: float = 30.0  # >0: stall watchdog over the in-flight
    #   window (resilience.supervisor) — a submitted batch older than this
    #   triggers recovery: shed the window, rebuild the engine (recompile,
    #   re-warm, re-calibrate), replace a wedged collect thread; open
    #   sessions survive with their frame index spaces intact. 0 = off.
    chaos: Any = None             # resilience.chaos.FaultPlan — arms the
    #   engine/assembler/collect injection sites (--chaos CLI spec)
    ingest: str = "streamed"      # "streamed": stage chosen frames into
    #   per-device-shard slabs, device_put each shard as it fills, submit
    #   the already-resident batch (runtime/ingest.py — the same streamed
    #   assembler the single-stream pipeline uses); "monolithic": the
    #   classic stage-all → engine.submit path
    ingest_depth: int = 4         # in-flight shard-transfer window
    egress: str = "streamed"      # result fetch path: "streamed" issues
    #   per-output-shard copy_to_host_async at submit and materializes
    #   into a preallocated host slab at collect (runtime/egress.py;
    #   auto-degrades where streaming cannot win); "monolithic" is the
    #   classic whole-batch np.asarray escape hatch
    replica_label: Optional[str] = None  # fleet tier: this frontend is
    #   replica N of a fleet — every fault record it emits carries the
    #   label, so the merged fleet export can attribute per-replica
    #   (resilience.faults.FaultStats). None outside a fleet.
    trace: bool = False           # arm this frontend's Tracer (bounded
    #   event ring, obs.trace): dispatch/device/H2D/D2H lanes, mergeable
    #   fleet-wide via Tracer.snapshot() — also the flight recorder's
    #   always-on black box
    telemetry_sample_s: float = 0.0  # TimeSeriesRing cadence: the bounded
    #   sliding window of load-control signals (fps, p50/p99, queue
    #   depth, SLO headroom, overlap efficiencies, per-kind fault rates)
    #   behind /timeseries and the burn-rate trigger. 0 = off (a window
    #   nothing reads is a per-second percentile merge wasted — the CLI
    #   turns it on with --metrics-port, and arming flight_dir enables
    #   it automatically at 1 Hz since the burn trigger reads it).
    flight_dir: Optional[str] = None  # SLO flight recorder: post-mortem
    #   dumps (merged trace + stats + telemetry window) land here when
    #   the watchdog trips, a fault budget overflows (frontend failure),
    #   or the SLO burn rate crosses slo_burn_threshold. None = off.
    flight_min_interval_s: float = 10.0  # dump rate limit
    flight_max_total_bytes: Optional[int] = 256 * 1024 * 1024  # on-disk
    #   bound across all dumps: past it the oldest are evicted (the
    #   newest always survives). None = count cap (max_dumps) only.
    slo_burn_threshold: float = 0.5  # fraction of a sampling window's
    #   deliveries missing their SLO that trips a flight dump (needs
    #   flight_dir + the telemetry ring); 0 disables the burn trigger
    flight_profile_s: float = 0.0  # >0: each dump also opens a
    #   jax.profiler capture window of this length (device lanes in the
    #   post-mortem); off by default — profiling is not free
    control: bool = False         # arm the load-adaptive control plane
    #   (dvf_tpu.control): closed-loop controllers over the telemetry
    #   ring actuating per-bucket batch size + tick budget, per-session
    #   resolution downshift (sr upscale return path), and the
    #   priority-tier admission floor (--control on the CLI)
    control_config: Any = None    # control.ControlConfig; None = defaults
    default_tier: int = 1         # tier for open_stream(tier=None):
    #   0 interactive (sheds last), 1 standard, 2 batch (sheds first)
    lineage: bool = False         # arm frame-lineage attribution
    #   (obs.lineage): every frame carries a span context through
    #   ingress → bucket queue → assemble/H2D → device → D2H → deliver,
    #   each delivered frame's components summing to its end-to-end
    #   latency; aggregates behind stats()['attribution'], signals()
    #   attr_*, and the explain() surface; SLO-breaching frames retain
    #   full lineage as flight-dump exemplars (--lineage on the CLI)
    lineage_exemplars: int = 64   # exemplar retention bound (breaches +
    #   slowest-K-per-window records kept for post-mortems)
    profile_dir: Optional[str] = None  # persist per-signature stage-cost
    #   profiles here (sibling of the compile cache): measured
    #   per-component costs written at bucket retirement/stop, loaded at
    #   bucket creation to seed tick-cost estimates and annotate
    #   control-plane decisions. None = no persistence.
    audit: bool = False           # the audit plane (obs.audit):
    #   sampled shadow-replay of delivered frames against a golden
    #   un-jitted jnp re-execution (every audit_sample_every-th staged
    #   frame, judged off the hot threads), plus the program-swap
    #   equivalence guard — every recompile adopted by a batch resize,
    #   quality rebind, or recovery rebuild ledgers a probe-digest
    #   verdict. Exports: stats()["audit"], audit_* signals,
    #   dvf_audit_* samples, /audit, flight-dump audit.json; the first
    #   CONFIRMED corruption trips a flight dump. Overhead gated ≤3%
    #   fps (benchmarks/AUDIT_BENCH.json). Off by default (--audit).
    audit_sample_every: int = 64  # shadow-replay sampling period K:
    #   every Kth staged frame is re-executed on the golden path
    audit_seed: int = 0           # sampler phase (deterministic replay)
    audit_tolerance: float = 2.0  # pinned max-abs-diff tolerance for
    #   chains whose compute leaves uint8 (jit-vs-unjit float rounding
    #   freedom); uint8_ok chains compare bit-exact regardless
    broadcast_sub_queue: int = 8  # broadcast plane (dvf_tpu.broadcast,
    #   built lazily at the first open_stream(publish=...)): default
    #   per-subscriber drop-oldest bound — a slow watcher drops its own
    #   frames, never the tier's
    broadcast_ingest_depth: int = 8   # publisher-tap → fan-out worker
    #   queue bound (drop-oldest: fan-out pressure sheds whole frames
    #   before any tier encodes them, the publisher never blocks)
    broadcast_evict_after: int = 32   # consecutive displaced puts before
    #   a dead subscriber is evicted from its lane
    broadcast_keyframe_interval: int = 16  # delta-tier keyframe cadence;
    #   also sets the per-tier forced-keyframe cooldown (interval // 2)
    broadcast_audit_wire: bool = False  # stamp every tier payload with
    #   the obs.audit envelope at the tier encoder — one stamp per tier
    #   per frame, verified by the FINAL subscriber even across relay
    #   hops (chaos `corrupt_wire` rides config.chaos)
    ledger: bool = True           # compile & reconfiguration ledger +
    #   memory accounting (obs.ledger / obs.memory): every compile,
    #   pool acquire/evict, batch resize, quality rebind, and engine
    #   rebuild lands as a structured event (cause, wall cost, measured
    #   bucket stall) in a bounded ring — stats()["ledger"], /ledger,
    #   the dvf_compile_ms histogram, dvf_mem_* gauges, a dedicated
    #   Perfetto lane, and flight-dump ledger.json. Default ON: events
    #   are reconfiguration-rate, not frame-rate (overhead gated ≤2%
    #   fps by benchmarks/LEDGER_BENCH.json). False = none of it.
    autoplan: bool = False        # auto-plan plane (control.planner):
    #   at startup, resolve an operating plan for the primary signature
    #   — plan-cache hit (warm restart: < 50 ms, no search), else a
    #   measured candidate search (analytic prune from the compile-time
    #   calibrations + stage profiles, then short paced bursts through
    #   THIS frontend for ≤ 1/3 of the grid), apply the winner (batch
    #   size, tick, ingest/egress + depth) and hand the PR 10
    #   controllers its envelope. Every decision ledgers as a PLAN
    #   event with its measured search cost (--autoplan on the CLI).
    autoplan_burst_frames: int = 48  # paced frames per live candidate
    #   leg (short on purpose: the search runs before traffic is
    #   admitted, and the analytic prune already did the ranking)
    plan_cache_dir: Optional[str] = None  # on-disk plan + calibration
    #   cache (control.plan_cache), sibling of the PR 9 compile cache:
    #   winning plans keyed by (signature, geometry, topology
    #   fingerprint, planner version); compile-time calibration triples
    #   keyed per topology — warm restarts skip both the plan search
    #   and the blocking calibration passes at engine compile. None
    #   with autoplan on = plan is searched but never persisted.


class _Bucket:
    """One serving signature's slice of the frontend.

    A bucket owns everything that is per-compiled-program: the leased
    ``Engine`` (from the frontend's :class:`ProgramPool`), the pinned
    frame geometry/dtype, its sessions, the streamed ingest assembler
    and egress fetcher built against THAT engine's shardings, a
    per-bucket :class:`ErrorBudget` (fault attribution is per bucket —
    one tenant mix's broken program must not spend another's budget),
    and the MEASURED tick-cost estimate the EDF/cost bucket scheduler
    scores it by (``Engine.step_block_ms`` calibration seed + an EWMA
    over observed batch wall times).
    """

    _EWMA_ALPHA = 0.2

    def __init__(self, config: "ServeConfig", filt: Filter, op_chain: str,
                 engine: Engine, key: Optional[SignatureKey] = None):
        self.config = config
        self.filter = filt
        self.op_chain = op_chain        # canonical chain spelling
        self.engine = engine
        self.key = key                  # SignatureKey once pinned
        self.sessions: Dict[str, StreamSession] = {}
        self.frame_shape: Optional[tuple] = (tuple(key.geometry)
                                             if key is not None else None)
        self.frame_dtype = key.np_dtype if key is not None else None
        self.budget = ErrorBudget(limit=config.fault_budget,
                                  window_s=config.fault_window_s)
        self.faults: Dict[str, int] = {}   # per-bucket kind counters
        self.inflight_batches = 0          # guarded by _count_lock:
        #   dispatch increments, collect decrements, recovery resets —
        #   an unsynchronized `+=` across those threads can lose an
        #   update and leave the counter pinned >0, which would make
        #   idle() permanently false (a silent admission outage at the
        #   bucket cap)
        self._count_lock = threading.Lock()
        self.batches = 0
        self.routed_frames = 0             # lifetime rows demuxed for
        #   this bucket (ResultRouter.route) — monotone across session
        #   retirement, unlike a per-live-session sum
        self.batch_size = config.batch_size  # per-bucket device batch
        #   rows — the control plane's batch controller resizes this
        #   from measured occupancy via a HOT SWAP: the successor
        #   program compiles aside while this bucket keeps dispatching
        #   at the old size; the commit swings the program pointer
        #   between ticks, and in-flight batches drain on the old
        #   program (their collect fetches through plan.fetcher)
        self.mean_valid_rows: Optional[float] = None  # EWMA of VALID
        #   rows per served batch — the occupancy signal batch sizing
        #   divides by (rows beyond it are padding the device computes
        #   and drops)
        self.ingest_mode = config.ingest
        self.degrade_reason: Optional[str] = None
        self.egress_mode = config.egress
        self.egress_degrade_reason: Optional[str] = None
        self.assembler: Optional[ShardedBatchAssembler] = None
        self.ingest_stats: Optional[IngestStats] = None
        self.fetcher: Optional[ShardedBatchFetcher] = None
        self.draining_fetchers: List[ShardedBatchFetcher] = []  # egress
        #   fetchers retired by a hot swap while batches prefetched into
        #   them were still in flight (those fetch through plan.fetcher);
        #   released by collect once the bucket's window drains to zero
        self.egress_stats: Optional[EgressStats] = None
        self._tick_cost_ms: Optional[float] = None  # live EWMA
        self.last_dispatch_t: Optional[float] = None  # wall clock of
        #   this bucket's most recent batch submit — the reconfiguration
        #   ledger measures a bucket stall as the gap in these ticks
        #   around an event (obs.ledger.ReconfigLedger.note_dispatch)
        self._label_cache: Optional[str] = None
        self._label_key: Optional[SignatureKey] = None
        self.stage_profile: Optional[dict] = None  # persisted
        #   per-signature stage-cost profile (obs.lineage), loaded at
        #   creation when the frontend has a profile_dir: measured
        #   component costs from PREVIOUS runs — seeds the tick-cost
        #   estimate before the first live sample and annotates
        #   control-plane decisions
        self._pooled = False  # engine leased/adopted in the ProgramPool

    # -- scheduling ------------------------------------------------------

    def tick_cost_estimate(self) -> float:
        """Measured per-batch cost in ms for the EDF/cost score: the
        live EWMA when ticks have been observed, else the compile-time
        step calibration, else a 1 ms floor (a bucket is never scored
        on a guess for longer than its first batch)."""
        if self._tick_cost_ms is not None:
            return self._tick_cost_ms
        cal = getattr(self.engine, "step_block_ms", None)
        if cal:
            return cal
        prof = self.stage_profile
        if prof and prof.get("tick_cost_ms"):
            # A previous run's MEASURED cost beats the 1 ms guess for
            # the window before this run's first live sample.
            return float(prof["tick_cost_ms"])
        return 1.0

    def observe_tick(self, wall_ms: float, sample: bool = True,
                     valid: Optional[int] = None) -> None:
        """Collect-side cost sample (submit → materialized, wall).
        ``sample=False`` counts the batch without feeding the cost EWMA —
        the wall time of a batch that queued behind other in-flight
        work measures the pipeline, not this bucket's program.
        ``valid`` (real rows in the batch) always feeds the occupancy
        EWMA: queueing doesn't contaminate a row count."""
        self.batches += 1
        a = self._EWMA_ALPHA
        if valid is not None:
            if self.mean_valid_rows is None:
                self.mean_valid_rows = float(valid)
            else:
                self.mean_valid_rows = ((1 - a) * self.mean_valid_rows
                                        + a * float(valid))
        if wall_ms <= 0 or not sample:
            return
        if self._tick_cost_ms is None:
            self._tick_cost_ms = wall_ms
        else:
            self._tick_cost_ms = (1 - a) * self._tick_cost_ms + a * wall_ms

    def record_fault(self, kind: str) -> None:
        self.faults[kind] = self.faults.get(kind, 0) + 1

    def adjust_inflight(self, delta: int) -> None:
        with self._count_lock:
            self.inflight_batches = max(0, self.inflight_batches + delta)

    def reset_inflight(self) -> None:
        with self._count_lock:
            self.inflight_batches = 0

    def release_drained_fetchers(self) -> None:
        """Free swap-retired egress fetchers; call only when no batch
        prefetched into them can still be in flight (window at zero, or
        the bucket is being torn down)."""
        drained, self.draining_fetchers = self.draining_fetchers, []
        for f in drained:
            f.release()

    # -- signature -------------------------------------------------------

    def pinned_signature(self) -> Optional[tuple]:
        """The per-frame (shape, dtype) this bucket is committed to: the
        engine's compiled signature when one exists, else the geometry
        pinned by the first submit/declaration. None = still free (the
        default bucket before any traffic)."""
        sig = self.engine.signature
        if sig is not None:
            (batch_shape, dtype) = sig
            return (tuple(batch_shape[1:]), np.dtype(dtype))
        if self.frame_shape is not None:
            return (tuple(self.frame_shape), np.dtype(self.frame_dtype))
        return None

    def idle(self) -> bool:
        """True when this bucket could retire right now: no live
        sessions and nothing in flight on the device."""
        return not self.sessions and self.inflight_batches == 0

    def label(self) -> str:
        # Cached: label() sits on per-frame paths (attribution fold,
        # router row accounting) and a render is a string build.
        key = self.key
        if key is not None:
            if self._label_cache is None or self._label_key is not key:
                self._label_cache = key.render()
                self._label_key = key
            return self._label_cache
        return f"{self.op_chain}|unpinned"

    # -- observability ---------------------------------------------------

    def stats_row(self) -> dict:
        live = list(self.sessions.values())
        agg = LatencyStats.merged([s.latency for s in live])
        row = {
            "signature": self.label(),
            "op_chain": self.op_chain,
            "batch_size": self.batch_size,
            "mean_valid_rows": self.mean_valid_rows,
            "open_sessions": len(live),
            "queue_depth": sum(len(s.ingress) + len(s.pending)
                               for s in live),
            "inflight_batches": self.inflight_batches,
            "batches": self.batches,
            "tick_cost_ms": self._tick_cost_ms
            if self._tick_cost_ms is not None
            else getattr(self.engine, "step_block_ms", None),
            "fps": agg.get("fps"),
            "p50_ms": agg.get("p50_ms"),
            "p99_ms": agg.get("p99_ms"),
            "routed_frames_total": self.routed_frames,
            "shed_total": sum(s.shed for s in live),
            "faults": dict(self.faults),
            "fault_budget": self.budget.summary(),
            "engine_batches": self.engine.stats.batches,
            "engine_compile_count": self.engine.stats.compile_count,
        }
        if self.ingest_stats is not None:
            row["ingest"] = self.ingest_stats.summary()
        if self.egress_stats is not None:
            row["egress"] = self.egress_stats.summary()
        return row


class ServeFrontend:
    """Multi-tenant serving frontend: signature buckets over one device
    (see module docstring)."""

    def __init__(
        self,
        filt: Filter,
        config: Optional[ServeConfig] = None,
        engine: Optional[Engine] = None,
    ):
        if filt.stateful:
            raise ValueError(
                f"filter {filt.name!r} is stateful; a shared batch "
                f"interleaves rows from different sessions, so temporal "
                f"state would leak across tenants — the serving frontend "
                f"only multiplexes stateless filters")
        self.filter = filt
        self.config = config or ServeConfig()
        if self.config.ingest not in INGEST_MODES:
            raise ValueError(
                f"ingest must be one of {INGEST_MODES}, got "
                f"{self.config.ingest!r}")
        if self.config.egress not in EGRESS_MODES:
            raise ValueError(
                f"egress must be one of {EGRESS_MODES}, got "
                f"{self.config.egress!r}")
        engine = engine or Engine(filt, chaos=self.config.chaos)
        if self.config.chaos is not None and engine.chaos is None:
            engine.chaos = self.config.chaos  # arm caller-built engine
        # Signature buckets: the DEFAULT bucket (index 0) carries the
        # constructor filter/engine and keeps the legacy single-
        # signature behavior (geometry pinned by the first submit or
        # declaration); further buckets are created at admission when a
        # session declares a different (op_chain, geometry, dtype).
        default_chain = canonical_op_chain_or_verbatim(filt.name)
        self.pool = ProgramPool(capacity=self.config.pool_capacity)
        self._buckets: List[_Bucket] = [
            _Bucket(self.config, filt, default_chain, engine)]
        self._bucket_by_key: Dict[SignatureKey, _Bucket] = {}
        # Live Filter objects by canonical chain. A filter's DISPLAY
        # name (e.g. "gaussian_blur(ksize=9)" resolved to its Pallas
        # impl) is not necessarily a buildable registry spec — so a new
        # geometry of an ALREADY-SERVED chain must reuse the existing
        # Filter object (filters are frozen dataclasses, shareable
        # across engines) instead of round-tripping through
        # build_filter. Only a never-seen chain builds from the spec.
        self._filters_by_chain: Dict[str, Filter] = {default_chain: filt}
        self.batcher = ContinuousBatcher(self.config.batch_size)
        self.router = ResultRouter()
        self._lock = threading.Lock()
        self._sessions: Dict[str, StreamSession] = {}
        self._retired: Dict[str, StreamSession] = {}   # closed; poll-able
        # Process-lifetime counter floor: sessions evicted from the
        # bounded retired map (or release()d) fold their totals in here,
        # so the *_total series stay MONOTONE — a Prometheus counter
        # that shrinks when an old tenant ages out reads as a reset and
        # fakes a rate() spike.
        self._evicted_totals: Dict[str, int] = {
            k: 0 for k in ("submitted", "delivered", "shed", "slo_miss",
                           "failed", "dropped_at_ingress")}
        self._ids = itertools.count()
        self.admission_rejections = 0
        self.errors = 0
        self.faults = FaultStats(replica=self.config.replica_label)
        #   per-kind counters + last errors (replica-attributed in a fleet)
        # -- continuity plane (resilience.continuity) ----------------------
        self.continuity = ContinuityStats()
        self._token_secret = new_secret()  # signs this frontend's resume
        #   tokens; a fleet snapshot persists its own fleet-level secret
        #   so tokens survive a front-door restart — this one is
        #   process-lifetime only (serve tier has no crash-recovery story
        #   of its own; the session state IS this process)
        # -- telemetry plane (obs/): tracer lanes, metrics registry,
        # sliding signal window, flight recorder ---------------------------
        label = self.config.replica_label
        self.tracer = Tracer(
            enabled=self.config.trace,
            process_name=f"serve:{label}" if label else "serve")
        self.registry = MetricsRegistry()
        attach_signal_provider(
            self.registry, "serve", self.signals,
            labels={"replica": label} if label else None)
        # -- compile & reconfiguration ledger + memory accounting ----------
        self.ledger: Optional[ReconfigLedger] = None
        self.compile_hist = None
        self.swap_hist = None
        self._leak_watch: Optional[LeakTrendWatch] = None
        if self.config.ledger:
            self.ledger = ReconfigLedger(tracer=self.tracer)
            # Every compile, labeled by canonical signature AND cause
            # (admission/resize/quality/recovery/precompile) — the
            # distribution the hot-swap work will be judged against.
            self.compile_hist = self.registry.histogram(
                "compile_ms", COMPILE_MS_BOUNDS)
            # Per-swap serving cost (the commit's measured wall on the
            # dispatch thread): the distribution the "stall-free"
            # claim is audited against — dvf_swap_stall_ms on /metrics.
            self.swap_hist = self.registry.histogram(
                "swap_stall_ms", SWAP_STALL_MS_BOUNDS)
            self.pool.observer = self._on_pool_event
            attach_memory_provider(self.registry,
                                   bucket_rows_fn=self._memory_bucket_rows)
            self._leak_watch = LeakTrendWatch()
        # -- audit plane (obs.audit): shadow replay + swap guard -----------
        self.audit: Optional[AuditPlane] = None
        if self.config.audit:
            self.audit = AuditPlane(
                sample_every=self.config.audit_sample_every,
                seed=self.config.audit_seed,
                tolerance=self.config.audit_tolerance,
                tracer=self.tracer,
                ledger=self.ledger,
                flight_cb=self._flight_trip,
                fault_cb=lambda e: self.faults.record(
                    FaultKind.INTEGRITY, e),
                label=f"serve-{label}" if label else "serve")
            attach_audit_provider(self.registry, self.audit)
        # -- frame-lineage attribution plane (obs.lineage) -----------------
        self.attribution: Optional[AttributionPlane] = None
        if self.config.lineage:
            self.attribution = AttributionPlane(
                exemplar_capacity=self.config.lineage_exemplars)
        # -- broadcast plane (dvf_tpu.broadcast) ---------------------------
        # Built lazily at the first open_stream(publish=...): plain
        # per-session serving pays nothing for the fan-out machinery.
        self.broadcast: Any = None
        # -- load-adaptive control plane (dvf_tpu.control) ----------------
        # Built BEFORE the ring so the ring cadence can come from the
        # control config; the plane's decisions ride the ring's
        # on_sample seam (chained with the SLO burn check below).
        self.control_plane = None
        self._admission_tier_floor: Optional[int] = None  # controller-
        #   set admission floor: open_stream refuses tier > floor
        self._tick_s = self.config.tick_s  # live dispatch tick (the
        #   control plane's tick-budget actuator writes it)
        self._pending_resizes: Dict[_Bucket, Any] = {}  # bucket →
        #   (n, reason): the dispatch thread kicks each off as a
        #   compile-aside (Engine.prepare_swap on a background thread;
        #   the bucket KEEPS dispatching at the old size throughout)
        self._pending_rebinds: "queue.Queue" = queue.Queue()  # (sid,
        #   key, level, reason, morph_chain) quality moves / morphs —
        #   applied by the dispatch thread, which owns the session
        #   pending deques being flushed
        self._pending_commits: "queue.Queue" = queue.Queue()  # staged
        #   hot swaps whose aside-compile finished: the dispatch thread
        #   commits each between ticks (one pointer swing — a batch
        #   never straddles the old and new programs)
        self._preparing_swaps: set = set()  # buckets with an aside-
        #   prepare in flight (one at a time per bucket; a newer
        #   pending resize waits its turn)
        self.swaps = 0        # committed hot swaps
        self.swap_aborts = 0  # failed prepares/commits (old program
        #   kept serving — the contained-abort contract)
        self.morphs = 0       # committed live filter-chain morphs
        self.quality_rebinds = 0
        self.quality_rebinds_dropped = 0
        self._warmed_quality: set = set()   # quality keys pre-compiled
        #   at admission time (control armed): the moment the quality
        #   controller needs the downshift program is mid-overload —
        #   the worst time to pay a compile on a busy host
        self.quality_flushed_frames = 0   # frames dropped by rebind
        #   flushes — kept OUT of shed_total (the pressure predicate
        #   reads shed deltas; the controller's own moves must not feed
        #   back as overload evidence)
        self.resize_compile_errors = 0
        # -- auto-plan plane (dvf_tpu.control.planner) --------------------
        self.applied_plan: Optional[dict] = None  # the Plan doc driving
        #   this frontend (autoplan() or a fleet front door applied it);
        #   None = the hand-set ServeConfig defaults
        self._topology: Optional[str] = None  # cached topology
        #   fingerprint (control.plan_cache) — the plan/calibration
        #   cache's invalidation axis; computed once from the mesh
        control_sample_s = 0.0
        if self.config.control:
            from dvf_tpu.control import ControlConfig, ControlPlane

            ccfg = self.config.control_config or ControlConfig()
            if ccfg.batch_max <= 0:
                # The compiled staging/slab pools size from the
                # frontend batch_size; the controller may shrink below
                # it, never grow past it.
                ccfg = dataclasses.replace(ccfg,
                                           batch_max=self.config.batch_size)
            self.control_plane = ControlPlane(self, ccfg)
            control_sample_s = ccfg.interval_s
        self.telemetry: Optional[TimeSeriesRing] = None
        sample_s = self.config.telemetry_sample_s or control_sample_s or (
            1.0 if self.config.flight_dir else 0.0)  # burn trigger +
        #   post-mortem window need the ring; plain serving doesn't pay
        if sample_s > 0:
            self.telemetry = TimeSeriesRing(
                self.signals,
                interval_s=sample_s,
                name="dvf-serve-telemetry",
                on_sample=self._on_telemetry_sample)
        self.flight: Optional[FlightRecorder] = None
        if self.config.flight_dir:
            self.flight = FlightRecorder(
                self.config.flight_dir,
                label=f"serve-{label}" if label else "serve",
                min_interval_s=self.config.flight_min_interval_s,
                max_total_bytes=self.config.flight_max_total_bytes,
                trace_fn=lambda: [self.tracer.snapshot()],
                stats_fn=self.stats,
                ring=self.telemetry,
                jax_profile_s=self.config.flight_profile_s,
                lineage_fn=(self.attribution.snapshot
                            if self.attribution is not None else None),
                ledger_fn=(self.ledger.document
                           if self.ledger is not None else None),
                audit_fn=(self.audit.document
                          if self.audit is not None else None))
        self.registry.register_provider(self._bucket_samples)
        #   per-bucket queue depth / p99 + the compile-cache counters
        #   (dvf_compile_cache_hits_total / _misses_total,
        #   dvf_pool_evictions_total) — unprefixed provider, so the
        #   series names are fleet-wide, not per-tier
        self._draining = False       # fleet drain hook: open_stream refuses
        self._retired_bucket_costs: Dict[str, Optional[float]] = {}
        #   label → tick_cost_ms of buckets retired for headroom —
        #   their measured costs must still persist at stop
        #   (profile_dir); recorded at retirement (no I/O under the
        #   admission lock), flushed by _persist_stage_profiles.
        #   Keyed by label (last retirement wins), so a churning server
        #   stays bounded by its distinct-signature count.
        self.recoveries = 0          # supervised engine rebuilds
        # Frontend-level budget = the default bucket's (fault budgets
        # attribute PER BUCKET — a broken signature's faults must not
        # spend another tenant mix's budget; non-bucket faults land here).
        self._budget = self._buckets[0].budget
        # Stall escalation is NOT time-windowed: stalls arrive at most
        # once per stall_timeout_s, so a sliding window can never fill.
        # Instead, consecutive recoveries with no successful batch in
        # between count up; a materialized batch resets the run. Past the
        # threshold the engine is declared unrecoverable.
        self._stalls_since_progress = 0
        self._stall_fail_after = max(2, self.config.fault_budget // 4)
        # In-flight registry (submit → materialize/discard), maintained
        # even with the watchdog off: budget-driven recovery must be able
        # to shed batches a wedged collect thread is holding.
        self._window = InflightWindow()
        self._supervisor: Optional[Supervisor] = None
        self._recovering = threading.Event()  # dispatch parks while set
        self._dispatch_parked = threading.Event()  # ack of that park
        self._dispatch_thread: Optional[threading.Thread] = None
        self._recover_lock = threading.Lock()
        self._collect_gen = 0  # bumped by recovery; a stale collect thread
        #   exits at its next loop check (and a wedged one, when it wakes)
        # Plain unbounded FIFO: depth is already bounded by the semaphore,
        # and drop-oldest semantics here would silently leak a permit and
        # the dropped batch's inflight claims.
        self._inflight: "queue.Queue" = queue.Queue()
        self._inflight_sem = threading.Semaphore(self.config.max_inflight)
        self._stop = threading.Event()
        self._dispatch_done = threading.Event()
        self._error: Optional[BaseException] = None
        self._threads: List[threading.Thread] = []

    @property
    def engine(self) -> Engine:
        """The DEFAULT bucket's engine — the legacy single-signature
        surface (tests monkeypatch its submit; the fleet's local factory
        hands one in). Multi-signature callers reach per-bucket engines
        through ``stats()['buckets']``/the pool."""
        return self._buckets[0].engine

    @engine.setter
    def engine(self, value: Engine) -> None:
        self._buckets[0].engine = value

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ServeFrontend":
        if self._threads:
            raise ServeError("frontend already started")
        self._threads = [
            threading.Thread(target=self._dispatch, name="dvf-serve-dispatch",
                             daemon=True),
            threading.Thread(target=self._collect, name="dvf-serve-collect",
                             daemon=True, args=(0,)),
        ]
        self._dispatch_thread = self._threads[0]
        for t in self._threads:
            t.start()
        if self.config.stall_timeout_s > 0:
            self._supervisor = Supervisor(
                self.config.stall_timeout_s, on_stall=self._on_stall,
                name="dvf-serve-supervisor", window=self._window,
                on_trip=self._flight_trip)
            self._supervisor.start()
        if self.control_plane is not None:
            self.control_plane.start()
        if self.telemetry is not None:
            self.telemetry.start()
        if self.audit is not None:
            self.audit.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful shutdown: stop batching new work, drain what's in
        flight, deliver every session's tail, retire all sessions."""
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.stop()
        if self.audit is not None:
            self.audit.stop()
        if self.control_plane is not None:
            self.control_plane.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry.sample_once()  # terminal row: a short run still
            #   leaves a window for the post-mortem/scrape to read
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=timeout)
        with self._lock:
            sessions = list(self._sessions.items())
            for sid, s in sessions:
                if s.bucket is not None:
                    s.bucket.sessions.pop(sid, None)
                self._retire_locked(sid, s)
            self._sessions.clear()
            buckets = list(self._buckets)
        for _, s in sessions:
            s.finalize()
        if self.broadcast is not None:
            # After the session tail delivery (finalize still taps) and
            # before device/slab release: fan-out workers, relays, and
            # tier codecs all join here — the conftest broadcast guard
            # pins that nothing outlives stop().
            self.broadcast.stop(timeout=timeout)
        # Release every compiled program's device residency: pooled
        # engines free through the pool; an engine that never made it
        # into the pool (default bucket that never compiled, adoption
        # race) frees directly. Idempotent — pinned by the conftest
        # session-end leak guard (runtime.engine.live_pool_engines).
        self.pool.close()
        for b in buckets:
            b.engine.free()
            # Release every bucket's host staging/delivery slabs
            # eagerly (the retirement path already does; live buckets
            # must too): the memory-accounting session-end guard pins
            # that a closed frontend leaves ZERO occupied host slabs.
            a, b.assembler = b.assembler, None
            f, b.fetcher = b.fetcher, None
            if a is not None:
                a.release()
            if f is not None:
                f.release()
            b.release_drained_fetchers()
            if self.ledger is not None:
                self.ledger.abandon_stalls(b.label())
        if self.config.profile_dir:
            # Persist this run's measured per-signature stage costs
            # (sibling of the compile cache): the next run's buckets —
            # and the topology planner — start from MEASURED numbers.
            self._persist_stage_profiles(buckets)
        if self._error is not None:
            raise self._error

    def _persist_stage_profiles(self, live_buckets) -> None:
        """Best-effort stage-cost persistence at stop: one profile per
        signature measured THIS run — live buckets plus buckets retired
        for headroom along the way (their tick costs were recorded at
        retirement; their attribution windows survive in the plane,
        keyed by label). Deduped by label (a re-admitted signature's
        window must not merge twice); a live bucket's newer tick cost
        wins over a retired record's. Never raises — profiles are
        optimization state, not worth failing a shutdown over."""
        with self._lock:
            pending: Dict[str, Optional[float]] = dict(
                self._retired_bucket_costs)
        for b in live_buckets:
            if b.key is None:
                continue
            tick = b._tick_cost_ms
            if tick is None:
                tick = getattr(b.engine, "step_block_ms", None)
            pending[b.key.render()] = tick
        for label, tick in pending.items():
            comps: dict = {}
            count = 0
            if self.attribution is not None:
                doc = self.attribution.bucket_profile_doc(label)
                if doc is not None:
                    comps = doc["components"]
                    count = doc["count"]
            if comps or tick:
                save_stage_profile(self.config.profile_dir, label,
                                   comps, tick_cost_ms=tick, count=count)

    def _bucket_stage_cost(self, bucket: "_Bucket") -> Optional[dict]:
        """Measured mean per-component cost for one bucket: the live
        attribution window when lineage is running, else the persisted
        profile from a previous run — what control-plane decisions are
        annotated with."""
        if self.attribution is not None:
            live = self.attribution.bucket_stage_cost_ms(bucket.label())
            if live:
                return live
        prof = bucket.stage_profile
        if prof and prof.get("components_ms"):
            return {k: round(float(v.get("mean_ms", 0.0)), 4)
                    for k, v in prof["components_ms"].items()}
        return None

    def __enter__(self) -> "ServeFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- replica-embeddable lifecycle (fleet drain hooks) ---------------

    def begin_drain(self) -> None:
        """Stop admitting new sessions; existing ones keep flowing.
        The first half of a fleet replica drain — reversible only by
        building a fresh frontend (a draining replica restarts, it does
        not un-drain)."""
        with self._lock:
            self._draining = True

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful replica drain: refuse new sessions, close every open
        session with ``drain=True`` (queued + in-flight frames still
        deliver), and wait until all of them have retired. Returns True
        when fully drained within ``timeout`` — False means frames may
        still be in flight (a broken engine can't serve its tail; the
        fleet tier writes those off as ``replica`` losses)."""
        self.begin_drain()
        with self._lock:
            sids = list(self._sessions)
        for sid in sids:
            try:
                self.close(sid, drain=True)
            except KeyError:
                pass  # retired between the snapshot and the close
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.open_count() == 0:
                return True
            if self._error is not None or self._stop.is_set():
                break
            time.sleep(0.005)
        return self.open_count() == 0

    def health(self) -> dict:
        """Cheap liveness/health export for a fleet monitor: no
        percentile work, no per-session scan — safe to poll at hertz
        rates over an RPC. ``ok`` is False once the frontend has failed
        hard (error budget exhausted / fail-fast fault): the fleet
        drains and replaces such a replica."""
        err = self._error
        return {
            "ok": err is None,
            "error": repr(err) if err is not None else None,
            "draining": self._draining,
            "open_sessions": self.open_count(),
            "recoveries": self.recoveries,
            "fault_total": self.faults.total(),
            "stalls": (self._supervisor.stalls
                       if self._supervisor is not None else 0),
            # Signatures this frontend serves without a cold compile —
            # what the fleet's signature-aware spillover prefers a
            # replica by, and what its rejections enumerate. Cheap: a
            # key-list copy, no percentile work.
            "warm_signatures": self._warm_signatures(),
        }

    def load_row(self) -> dict:
        """The per-replica load summary the fleet monitor caches for
        its ELASTIC view (rides the health RPC, one row per poll):
        queue depth, occupancy, the monotone delivery/shed/refusal
        counters, and one weighted percentile merge — ``signals()``'s
        only aggregate cost, at ``health()``'s cadence. Everything the
        fleet elasticity controller reads per replica, nothing more."""
        with self._lock:
            live = list(self._sessions.values())
            retired = list(self._retired.values())
            floor = dict(self._evicted_totals)
        every = retired + live
        agg = LatencyStats.merged([s.latency for s in every])
        p99 = agg.get("p99_ms")
        return {
            "open_sessions": float(len(live)),
            "queue_depth": float(sum(
                len(s.ingress) + len(s.pending) for s in live)),
            "p99_ms": p99 if (p99 is not None and p99 == p99) else None,
            "delivered_total": float(floor["delivered"] + sum(
                s.delivered for s in every)),
            "shed_total": float(floor["shed"] + sum(
                s.shed for s in every)),
            "slo_miss_total": float(floor["slo_miss"] + sum(
                s.slo_miss for s in every)),
            "admission_rejections_total": float(self.admission_rejections),
        }

    def latency_snapshot(self) -> dict:
        """All sessions' latency samples as ONE mergeable snapshot
        (``LatencyStats.combined``) — the per-replica half of the fleet
        p50/p99 export; the front door merges replicas' snapshots with
        ``LatencyStats.merge_snapshots``."""
        with self._lock:
            every = {**self._retired, **self._sessions}
        return LatencyStats.combined([s.latency for s in every.values()])

    def signals(self) -> dict:
        """The flat load-control signal set — one dict, registry-
        conformant keys, cheap enough to sample at hertz rates: what the
        TimeSeriesRing windows, the ``/metrics`` provider scrapes
        (``obs.export.samples_from_signals``), and a load-adaptive
        controller would read. Counter reads are GIL-atomic ints; the
        only aggregate math is one weighted percentile merge."""
        with self._lock:
            live = list(self._sessions.values())
            retired = list(self._retired.values())
            floor = dict(self._evicted_totals)
            buckets = list(self._buckets)
        every = retired + live
        agg = LatencyStats.merged([s.latency for s in every])
        p99 = agg.get("p99_ms")
        headroom = (self.config.slo_ms - p99
                    if p99 is not None and p99 == p99 else None)
        out = {
            "fps": agg.get("fps"),
            "p50_ms": agg.get("p50_ms"),
            "p90_ms": agg.get("p90_ms"),
            "p99_ms": agg.get("p99_ms"),
            "slo_headroom_ms": headroom,
            # Standing work: frames queued before a device slot plus
            # batches in flight — the queueing-delay signal a dynamic
            # batch/tick controller keys off.
            "queue_depth": float(sum(
                len(s.ingress) + len(s.pending) for s in live)),
            "inflight_batches": float(len(self._window)),
            "open_sessions": float(len(live)),
            "retired_sessions": float(len(retired)),
            # Lifetime counters: live + retired sessions PLUS the floor
            # absorbed from evicted ones — monotone across retirement-
            # bound churn (a counter must never go backward).
            "submitted_total": float(floor["submitted"] + sum(
                s.submitted for s in every)),
            "delivered_total": float(floor["delivered"] + sum(
                s.delivered for s in every)),
            "shed_total": float(floor["shed"] + sum(
                s.shed for s in every)),
            "slo_miss_total": float(floor["slo_miss"] + sum(
                s.slo_miss for s in every)),
            "failed_total": float(floor["failed"] + sum(
                s.failed for s in every)),
            "dropped_at_ingress_total": float(
                floor["dropped_at_ingress"] + sum(
                    s.ingress.dropped for s in every)),
            "admission_rejections_total": float(self.admission_rejections),
            "errors_total": float(self.errors),
            "recoveries_total": float(self.recoveries),
            "engine_batches_total": float(sum(
                b.engine.stats.batches for b in buckets)),
            "engine_frames_total": float(sum(
                b.engine.stats.frames for b in buckets)),
            "trace_dropped_total": float(self.tracer.dropped),
            # Multi-signature plane: live buckets + the compiled-program
            # pool's hit/miss/eviction counters (the admission-cost
            # story: a hit is a warm admit, a miss a cold compile).
            "open_buckets": float(len(buckets)),
            "compile_cache_hits_total": float(self.pool.hits),
            "compile_cache_misses_total": float(self.pool.misses),
            "pool_evictions_total": float(self.pool.evictions),
            "pool_size": float(len(self.pool)),
            # Hot-swap plane: committed program swaps, contained aborts
            # (old program kept serving), live filter-chain morphs.
            "swaps_total": float(self.swaps),
            "swap_aborts_total": float(self.swap_aborts),
            "morphs_total": float(self.morphs),
        }
        out.update(self.continuity.signals())
        if self._supervisor is not None:
            out["stalls_total"] = float(self._supervisor.stalls)
        if self.control_plane is not None:
            # Control-plane decision counters (the acceptance bar:
            # controller actions are observable on the scrape endpoint)
            # plus the live actuation state.
            for k, v in self.control_plane.signals().items():
                out[f"control_{k}"] = v
            out["control_quality_rebinds_total"] = float(
                self.quality_rebinds)
            out["control_quality_rebinds_dropped_total"] = float(
                self.quality_rebinds_dropped)
            out["control_quality_flushed_frames_total"] = float(
                self.quality_flushed_frames)
            out["control_resize_compile_errors_total"] = float(
                self.resize_compile_errors)
            out["downshifted_sessions"] = float(sum(
                1 for s in live if s.quality_level > 0))
            out["dispatch_tick_s"] = float(self._tick_s)
        ing = self._buckets[0].ingest_stats
        egr = self._buckets[0].egress_stats
        if ing is not None:
            out["ingest_overlap_efficiency"] = ing.overlap_efficiency()
        if egr is not None:
            out["egress_overlap_efficiency"] = egr.overlap_efficiency()
        if self.ledger is not None:
            out.update(self.ledger.signals())
            # Occupied host staging/delivery slabs (cheap per-bucket
            # sums) — also the leak-trend watch's input via the ring.
            slab, state = self._slab_state_bytes(buckets)
            out["mem_host_slab_bytes"] = float(slab)
            out["mem_device_state_bytes"] = float(state)
        if self.attribution is not None:
            # Frame-lineage attribution: per-component p99 over the
            # window (attr_<component>_p99_ms) + lineage counters —
            # the "where did my p99 go" row, scrapeable per second.
            out.update(self.attribution.signals())
        if self.audit is not None:
            out.update(self.audit.signals())
        if self.broadcast is not None:
            out.update(self.broadcast.signals())
        for kind, n in self.faults.summary()["by_kind"].items():
            out[f"fault_{kind}_total"] = float(n)
        return out

    def audit_probe(self, signature: Optional[str] = None) -> dict:
        """Run the deterministic probe frame through one compiled
        bucket's program and return its output digest — the unit the
        fleet's cross-replica divergence detector compares (every
        replica derives the SAME probe pixels from the signature, so
        equal programs must produce equal digests). ``signature``
        (a canonical render) picks the bucket; None probes the first
        compiled one. Raises ``ServeError`` when nothing is compiled —
        the fleet counts that replica as unprobeable, it does not
        judge it."""
        from dvf_tpu.obs.audit import engine_probe_row, frame_digest

        with self._lock:
            buckets = list(self._buckets)
        engine = None
        label = None
        for b in buckets:
            if b.engine.signature is None or b.engine.freed:
                continue
            if signature is None or b.label() == signature:
                engine, label = b.engine, b.label()
                break
        if engine is None:
            # Pool-warm fallback: "warm on a signature" includes
            # programs whose bucket retired (or that only ever
            # precompiled) — health() advertises exactly those, so the
            # fleet's divergence check must be able to probe them too.
            for key in sorted(self.pool.warm_keys(),
                              key=lambda k: k.render()):
                if signature is None or key.render() == signature:
                    cand = self.pool.peek(key)
                    if cand is not None and not cand.freed \
                            and cand.signature is not None:
                        engine, label = cand, key.render()
                        break
        if engine is None:
            raise ServeError(
                f"no compiled program to probe"
                + (f" for signature {signature!r}" if signature else ""))
        row = engine_probe_row(engine)
        return {"signature": label,
                "digest": frame_digest(row).hex()}

    def explain(self, q: float = 99.0) -> dict:
        """The latency-attribution ``explain`` surface: which components
        the slowest frames actually spent their time in, frontend-wide
        and per bucket — "p99 = 62% queue_bucket, 21% device, …". Empty
        when lineage is not armed (``ServeConfig.lineage``)."""
        if self.attribution is None:
            return {"lineage": False,
                    "hint": "arm ServeConfig.lineage / --lineage to "
                            "collect frame-lineage attribution"}
        return {"lineage": True, **self.attribution.explain(q)}

    def _bucket_samples(self) -> List[MetricSample]:
        """Registry provider: the per-bucket load/latency series
        (``bucket=`` label carries the canonical signature) plus the
        frontend-wide compile-cache counters — unprefixed, so the
        series are ``dvf_compile_cache_hits_total`` /
        ``dvf_bucket_queue_depth{bucket=…}`` etc. on the scrape."""
        out = [
            MetricSample("compile_cache_hits_total",
                         float(self.pool.hits), (), COUNTER),
            MetricSample("compile_cache_misses_total",
                         float(self.pool.misses), (), COUNTER),
            MetricSample("pool_evictions_total",
                         float(self.pool.evictions), (), COUNTER),
            MetricSample("pool_size", float(len(self.pool)), (), GAUGE),
        ]
        # Snapshot under the lock, merge percentiles AFTER releasing it
        # (stats()'s discipline): a scrape must not stall submit/open/
        # dispatch behind per-bucket percentile math.
        with self._lock:
            snap = [(b, list(b.sessions.values())) for b in self._buckets]
        rows = []
        for b, live in snap:
            rows.append((
                b.label(),
                sum(len(s.ingress) + len(s.pending) for s in live),
                len(live),
                b.inflight_batches,
                b.tick_cost_estimate(),
                LatencyStats.merged([s.latency for s in live]),
            ))
        for label, qd, n_live, inflight, cost, agg in rows:
            labels = (("bucket", label),)
            out.append(MetricSample("bucket_queue_depth", float(qd),
                                    labels, GAUGE))
            out.append(MetricSample("bucket_open_sessions", float(n_live),
                                    labels, GAUGE))
            out.append(MetricSample("bucket_inflight_batches",
                                    float(inflight), labels, GAUGE))
            out.append(MetricSample("bucket_tick_cost_ms", float(cost),
                                    labels, GAUGE))
            for pk in ("p50_ms", "p99_ms"):
                v = agg.get(pk)
                if v is not None and v == v:  # NaN (empty window) = gap
                    out.append(MetricSample(f"bucket_{pk}", float(v),
                                            labels, GAUGE))
        return out

    def _on_telemetry_sample(self, prev: Optional[dict], cur: dict) -> None:
        """The ring's on_sample hook: SLO burn check, then the control
        plane's decision step. Each leg is independently contained (the
        ring counts a raising hook in hook_errors_total and keeps
        sampling, but a burn-check hiccup must not also cost the
        controller its tick)."""
        try:
            self._check_slo_burn(prev, cur)
        except Exception:  # noqa: BLE001 — the controller still runs
            if self.control_plane is None:
                raise  # sole hook: let the ring count it
            if self.telemetry is not None:
                # Swallowed so the controller keeps its tick, but a
                # broken burn trigger must stay visible on the same
                # containment counter a raising hook lands on.
                self.telemetry.hook_errors += 1
        if self._leak_watch is not None:
            try:
                trip = self._leak_watch.observe(
                    cur.get("mem_host_slab_bytes"))
                if trip is not None:
                    self._flight_trip(trip)
            except Exception:  # noqa: BLE001 — same containment rule as
                if self.telemetry is not None:  # the burn check above
                    self.telemetry.hook_errors += 1
        if self.control_plane is not None:
            self.control_plane.on_sample(prev, cur)

    def _check_slo_burn(self, prev: Optional[dict], cur: dict) -> None:
        """Telemetry-ring hook: burn rate over one sampling window =
        fraction of the window's deliveries that missed their SLO; past
        the threshold, the flight recorder dumps (rate-limited there)."""
        threshold = self.config.slo_burn_threshold
        if self.flight is None or threshold <= 0 or prev is None:
            return
        delivered = (cur.get("delivered_total", 0)
                     - prev.get("delivered_total", 0))
        if delivered <= 0:
            return
        missed = cur.get("slo_miss_total", 0) - prev.get("slo_miss_total", 0)
        burn = missed / delivered
        if burn >= threshold:
            self.flight.trigger(
                f"slo burn rate {burn:.2f} >= {threshold:g} "
                f"({missed:.0f}/{delivered:.0f} deliveries past "
                f"{self.config.slo_ms:g}ms in one window)")

    def _flight_trip(self, reason: str) -> None:
        """Observability tap for failure events (watchdog on_trip,
        budget-exhaustion _fail): dump the black box OFF-THREAD
        (FlightRecorder.trigger_async) — the callers are the supervisor
        and recovery paths, and serializing a trace window to disk must
        not extend the stall it is recording."""
        if self.flight is not None:
            self.flight.trigger_async(reason)

    # -- reconfiguration ledger + memory accounting ----------------------

    def _on_pool_event(self, kind: str, cause=None, key=None, cache=None,
                       wall_ms=None, engine=None, **_extra) -> None:
        """ProgramPool observer: pool hits, cold compiles, and evictions
        land in the ledger; compiles also feed the dvf_compile_ms
        histogram. Called outside the pool lock; never raises into a
        lease (the pool swallows, but stay cheap anyway)."""
        led = self.ledger
        if led is None:
            return
        sig = key.render() if hasattr(key, "render") else (
            str(key) if key is not None else None)
        cause = cause or ledger_mod.CAUSE_ADMISSION
        if kind == "compile":
            compile_ms = getattr(engine, "last_compile_ms", None)
            if compile_ms is None:
                compile_ms = wall_ms
            led.record(ledger_mod.COMPILE, cause=cause, signature=sig,
                       cache=cache, wall_ms=wall_ms,
                       compile_ms=(round(float(compile_ms), 3)
                                   if compile_ms is not None else None))
            self._observe_compile(compile_ms, sig, cause)
        elif kind == "pool_acquire":
            led.record(ledger_mod.POOL_ACQUIRE, cause=cause,
                       signature=sig, cache=cache, wall_ms=0.0)
        elif kind == "pool_evict":
            led.record(ledger_mod.POOL_EVICT, cause=cause, signature=sig,
                       freed_bytes=getattr(engine, "state_bytes", None))

    def _observe_compile(self, compile_ms, signature, cause) -> None:
        if self.compile_hist is not None and compile_ms is not None:
            self.compile_hist.observe(
                float(compile_ms),
                labels={"signature": signature or "unpinned",
                        "cause": cause or "unknown"})

    def _observe_swap(self, stall_ms, signature, cause) -> None:
        """The ``dvf_swap_stall_ms`` histogram: the measured serving
        time one hot swap consumed (the commit's pointer swing — ~0),
        NOT the aside-compile (nobody was blocked for that)."""
        if self.swap_hist is not None and stall_ms is not None:
            self.swap_hist.observe(
                float(stall_ms),
                labels={"signature": signature or "unpinned",
                        "cause": cause or "unknown"})

    def _record_inline_compile(self, bucket: "_Bucket", before: int,
                               cause: str) -> None:
        """Ledger a compile that ran OUTSIDE the pool (the default
        bucket's lazy first pin in ``_builder_for``, a resize's
        recompile): ``before`` is the engine's compile_count before the
        ``ensure_compiled`` call — unchanged means no compile ran."""
        led = self.ledger
        eng = bucket.engine
        if led is None or eng.stats.compile_count == before:
            return
        sig = bucket.label()
        compile_ms = eng.last_compile_ms
        led.record(ledger_mod.COMPILE, cause=cause, signature=sig,
                   bucket=sig, cache="miss",
                   wall_ms=compile_ms,
                   compile_ms=(round(float(compile_ms), 3)
                               if compile_ms is not None else None))
        self._observe_compile(compile_ms, sig, cause)

    def _memory_bucket_rows(self) -> List[dict]:
        """Per-bucket memory attribution for the dvf_mem_* gauges:
        device-resident state (measured at compile) + occupied host
        staging/delivery slabs. Scrape-time only."""
        with self._lock:
            buckets = list(self._buckets)
        rows = []
        for b in buckets:
            a, f = b.assembler, b.fetcher
            rows.append({
                "bucket": b.label(),
                "device_state_bytes": getattr(b.engine, "state_bytes", 0),
                "host_slab_bytes": ((a.slab_bytes() if a is not None else 0)
                                    + (f.slab_bytes()
                                       if f is not None else 0)),
            })
        return rows

    @staticmethod
    def _slab_state_bytes(buckets) -> tuple:
        """(host slab bytes, device state bytes) over an
        already-snapshotted bucket list — ONE copy of the sum shared by
        signals() and _host_slab_bytes. Fields are captured once per
        bucket: a concurrent resize/recovery nulls b.assembler under
        the frontend lock, and a check-then-call would race it."""
        slab = state = 0
        for b in buckets:
            a, f = b.assembler, b.fetcher
            if a is not None:
                slab += a.slab_bytes()
            if f is not None:
                slab += f.slab_bytes()
            state += getattr(b.engine, "state_bytes", 0) or 0
        return slab, state

    def _host_slab_bytes(self) -> int:
        """This frontend's occupied host staging memory (cheap sums —
        a handful of buckets), the signals()/leak-watch input."""
        with self._lock:
            buckets = list(self._buckets)
        return self._slab_state_bytes(buckets)[0]

    def _memory_stats(self) -> dict:
        """The ``stats()['memory']`` row: per-bucket attributed host
        slabs + device state. The process-wide jax live-buffer WALK is
        deliberately absent here — it runs only on the /metrics scrape
        (obs.memory.attach_memory_provider), never in a stats() poll
        loop."""
        rows = self._memory_bucket_rows()
        return {
            "host_slab_bytes": sum(r["host_slab_bytes"] for r in rows),
            "device_state_bytes": sum(r["device_state_bytes"]
                                      for r in rows),
            "by_bucket": {r["bucket"]: {
                "host_slab_bytes": r["host_slab_bytes"],
                "device_state_bytes": r["device_state_bytes"],
            } for r in rows},
            "pool": {
                "engines": len(self.pool),
            },
        }

    # -- client API ------------------------------------------------------

    def open_stream(
        self,
        session_id: Optional[str] = None,
        slo_ms: Optional[float] = None,
        sink: Any = None,
        frame_shape: Optional[tuple] = None,
        frame_dtype: Any = None,
        op_chain: Optional[str] = None,
        tier: Optional[int] = None,
        publish: Optional[str] = None,
        publish_tiers: Optional[Sequence] = None,
    ) -> str:
        """Admit one new stream; returns its session id.

        ``publish`` registers the session's delivered output as a named
        broadcast channel (dvf_tpu.broadcast): subscribers attach with
        :meth:`subscribe` at a (geometry, quality, wire) tier —
        ``publish_tiers`` pre-registers the ladder (tier specs like
        ``"640x360/q60/delta"`` or :class:`~dvf_tpu.broadcast.Tier`).
        The publisher's own poll()/sink delivery is unchanged; fan-out
        rides a per-delivery tap behind it.

        Raises ``AdmissionError`` at the ``max_sessions`` cap — overload
        is refused at the door, not absorbed as unbounded queueing — and
        when the frontend is draining (fleet replica teardown).

        ``tier`` is the stream's priority tier (0 interactive, 1
        standard, 2 batch; default ``config.default_tier``): under
        sustained overload the control plane's admission floor refuses
        the highest tiers first, the batcher's slot pick prefers lower
        tiers, and the quality controller downshifts higher tiers first
        — paid/interactive streams shed LAST end to end.

        ``op_chain``/``frame_shape``/``frame_dtype`` declare the
        stream's signature at admission time and ROUTE it: a declaration
        matching a live bucket (or the default bucket's pin) joins that
        bucket; a new signature ADMITS BY CREATING a bucket — its
        program is compiled here, ahead of the first frame
        (``jit → lower → compile`` through the program pool and the
        persistent compilation cache, so a previously-seen signature
        costs milliseconds), never as a JIT stall on the serving path.
        Only past ``max_buckets`` (with no idle bucket to retire) is a
        new signature refused — and the refusal enumerates the warm
        signatures this frontend can serve cheaply. An undeclared open
        joins the default bucket, whose geometry pins at first submit
        (the legacy single-signature behavior, unchanged).
        """
        t = self.config.default_tier if tier is None else int(tier)
        if t < 0:
            raise ValueError(f"tier must be >= 0, got {tier!r}")
        cfg = SessionConfig(
            queue_size=self.config.queue_size,
            slo_ms=slo_ms if slo_ms is not None else self.config.slo_ms,
            frame_delay=self.config.frame_delay,
            reorder_capacity=self.config.reorder_capacity,
            out_queue_size=self.config.out_queue_size,
            tier=t,
            replay_window=self.config.replay_window,
        )
        declared = None
        if frame_shape is not None:
            # canonical_dtype, NOT np.dtype: the ML spelling "u8" means
            # uint8, while numpy alone reads it as an 8-BYTE uint64.
            declared = (tuple(int(d) for d in frame_shape),
                        canonical_dtype(frame_dtype))
        elif frame_dtype is not None:
            raise ValueError("frame_dtype given without frame_shape")
        chain = None
        if op_chain is not None:
            try:
                chain = canonical_op_chain(op_chain)
            except ValueError as e:
                with self._lock:
                    self.admission_rejections += 1
                raise AdmissionError(f"malformed op_chain: {e}") from e
        with self._lock:
            self._check_admission_locked(tier=t)
            bucket, create_key = self._route_locked(chain, declared)
            if bucket is not None:
                self._price_admission_locked(bucket, t, cfg.slo_ms)
                sid_out = self._register_session_locked(
                    bucket, session_id, cfg, sink)
        if bucket is not None:
            self._warm_quality_async(bucket)
            if publish:
                self.publish_stream(sid_out, publish, publish_tiers)
            return sid_out
        with self._lock:
            # Best-effort headroom check BEFORE the compile: a frontend
            # at the bucket cap with no idle victim must refuse now, not
            # after seconds of JIT whose orphan program would then sit
            # in the pool advertising a signature this frontend cannot
            # actually serve. _create_bucket_locked re-checks
            # authoritatively (state may change while we compile).
            self._check_bucket_headroom_locked(create_key)
        # New signature: build/lease its compiled program OUTSIDE the
        # frontend lock — a cold compile must not stall dispatch of the
        # other buckets (that is the JIT stall this design removes from
        # the serving path); the pool's per-key latch dedups concurrent
        # admits of the same signature.
        engine = self._acquire_program(create_key)
        owned = False
        try:
            with self._lock:
                self._check_admission_locked(tier=t)
                bucket = self._bucket_by_key.get(create_key)
                if bucket is None:
                    bucket = self._create_bucket_locked(create_key, engine)
                    owned = True
                sid_out = self._register_session_locked(
                    bucket, session_id, cfg, sink)
        finally:
            if not owned:
                # Either the signature raced into existence (join — our
                # extra lease drops; the bucket keeps its own) or
                # admission failed after the lease: the program stays
                # WARM in the pool either way.
                self.pool.release(create_key)
        self._warm_quality_async(bucket)
        if publish:
            self.publish_stream(sid_out, publish, publish_tiers)
        return sid_out

    # -- broadcast plane (publish / subscribe) ---------------------------

    def _ensure_broadcast(self):
        if self.broadcast is None:
            from dvf_tpu.broadcast import BroadcastPlane

            c = self.config
            self.broadcast = BroadcastPlane(
                audit_wire=c.broadcast_audit_wire, chaos=c.chaos,
                ingest_depth=c.broadcast_ingest_depth,
                sub_queue=c.broadcast_sub_queue,
                evict_after=c.broadcast_evict_after,
                keyframe_interval=c.broadcast_keyframe_interval,
                lineage=self.attribution is not None)
        return self.broadcast

    def publish_stream(self, session_id: str, channel: str,
                       tiers: Optional[Sequence] = None) -> None:
        """Register an open session's delivered output as broadcast
        channel ``channel``. The session keeps its own delivery path
        (poll/sink); the broadcast tap tees each delivered frame into
        the channel's fan-out worker (one copy + one bounded enqueue —
        a stalled fan-out sheds frames there, never the publisher)."""
        plane = self._ensure_broadcast()
        plane.publish(channel, publisher=session_id, tiers=tiers or ())
        with self._lock:
            s = self._sessions.get(session_id)
        if s is None:
            plane.unpublish(channel)
            raise ServeError(f"no open session {session_id!r} to publish")
        s.tap = plane.tap(channel)

    def subscribe(self, channel: str, tier=None,
                  queue_size: Optional[int] = None, abr: bool = False):
        """Attach a watcher to a published channel at a tier (spec
        string or :class:`~dvf_tpu.broadcast.Tier`; None = the ladder
        top, or its cheapest rung when ``abr`` is on). Returns the
        :class:`~dvf_tpu.broadcast.Subscription` handle (``poll`` /
        ``stats``; pass back to :meth:`unsubscribe`)."""
        return self._ensure_broadcast().subscribe(
            channel, tier=tier, queue_size=queue_size, abr=abr)

    def unsubscribe(self, sub) -> None:
        if self.broadcast is not None:
            self.broadcast.unsubscribe(sub)

    # -- admission internals (bucket routing) ---------------------------

    def _check_admission_locked(self, tier: Optional[int] = None) -> None:
        if self._draining:
            self.admission_rejections += 1
            raise AdmissionError(
                "frontend is draining (no new sessions admitted)")
        floor = self._admission_tier_floor
        if tier is not None and floor is not None and tier > floor:
            # Controller-set load shed at the door: the cheapest place
            # to refuse work is before any of it is queued. Graceful by
            # contract — a refused low-tier open is degradation, not a
            # failure (the fleet tier spills it to a replica with
            # headroom when one exists).
            self.admission_rejections += 1
            raise AdmissionError(
                f"tier {tier} not admitted under overload (admission "
                f"floor {floor}: the load controller is shedding "
                f"low-priority sessions first)")
        if len(self._sessions) >= self.config.max_sessions:
            self.admission_rejections += 1
            raise AdmissionError(
                f"session limit reached ({self.config.max_sessions} "
                f"open); close a stream or raise max_sessions")

    def _price_admission_locked(self, bucket: "_Bucket", tier: int,
                                slo_ms: float) -> None:
        """Feed-forward admission pricing (the auto-plan plane's third
        leg, armed by ``config.autoplan``): BEFORE a tenant is
        admitted, predict what its bucket's scheduling round will cost
        with it aboard — from the persisted stage-cost profile
        (obs.lineage) a previous run measured, else the live tick
        EWMA — and refuse a non-interactive tenant whose predicted
        steady-state latency already breaches its own SLO. The
        reactive tier controller (control.controllers) refuses AFTER
        queues build and refusals advance; this prices the marginal
        tenant from the profile so the refusal lands before its first
        frame is ever queued. Nothing measured yet → admit (the cold
        path stays reactive, exactly as before this plane)."""
        if not self.config.autoplan or tier <= 0:
            return
        from dvf_tpu.control.planner import predicted_tick_cost_ms
        cost = predicted_tick_cost_ms(bucket.stage_profile,
                                      batch_size=bucket.batch_size)
        if cost is None:
            cost = bucket._tick_cost_ms
        if not cost:
            return
        occupants = len(bucket.sessions) + 1
        rounds = -(-occupants // max(1, bucket.batch_size))  # ceil
        predicted_ms = float(cost) * rounds
        if predicted_ms > float(slo_ms):
            self.admission_rejections += 1
            raise AdmissionError(
                f"admission priced out (feed-forward): predicted "
                f"steady-state latency {predicted_ms:.1f} ms for "
                f"tenant {occupants} of bucket {bucket.label()!r} "
                f"(predicted tick {float(cost):.2f} ms x {rounds} "
                f"scheduling rounds) exceeds its {float(slo_ms):g} ms "
                f"SLO; warm signatures this frontend serves cheaply: "
                f"{self._warm_signatures()}")

    def _route_locked(
        self, chain: Optional[str], declared: Optional[tuple],
    ) -> Tuple[Optional["_Bucket"], Optional[SignatureKey]]:
        """Map a declaration to ``(bucket, None)`` (join) or
        ``(None, key)`` (create a bucket for ``key``)."""
        default = self._buckets[0]
        if chain is None and declared is None:
            return default, None  # legacy: default bucket, pin at submit
        chain = chain if chain is not None else default.op_chain
        if declared is None:
            # op_chain alone: join the one live bucket serving it.
            matches = [b for b in self._buckets if b.op_chain == chain]
            if len(matches) == 1:
                return matches[0], None
            self.admission_rejections += 1
            raise AdmissionError(
                f"op_chain {chain!r} needs frame_shape to admit "
                f"({len(matches)} live buckets serve it); warm "
                f"signatures: {self._warm_signatures()}")
        shape, dtype = declared
        key = make_key(chain, shape, dtype)
        b = self._bucket_by_key.get(key)
        if b is not None:
            return b, None
        if chain == default.op_chain:
            pinned = default.pinned_signature()
            if pinned is None:
                # First declaration pins the default bucket (the legacy
                # seam, now one bucket among several).
                default.frame_shape = tuple(key.geometry)
                default.frame_dtype = key.np_dtype
                default.key = key
                if self.config.profile_dir:
                    default.stage_profile = load_stage_profile(
                        self.config.profile_dir, key.render())
                self._bucket_by_key[key] = default
                return default, None
            if pinned == (tuple(key.geometry), key.np_dtype):
                # Same signature spelled differently / pinned by a
                # first submit before any declaration: join.
                if default.key is None:
                    default.key = key
                self._bucket_by_key.setdefault(key, default)
                return default, None
        return None, key

    def _register_session_locked(self, bucket: "_Bucket",
                                 session_id: Optional[str],
                                 cfg: SessionConfig, sink: Any) -> str:
        sid = session_id if session_id is not None else f"s{next(self._ids)}"
        if sid in self._sessions or sid in self._retired:
            raise ServeError(f"session id {sid!r} already exists")
        s = StreamSession(sid, cfg, sink=sink)
        s.bucket = bucket
        s.attribution = self.attribution  # None when lineage is off
        self._sessions[sid] = s
        bucket.sessions[sid] = s
        return sid

    def _check_bucket_headroom_locked(self, key: SignatureKey) -> None:
        """Refuse a new-signature admission when the bucket cap is
        reached and nothing can retire (counts the rejection). Shared by
        the pre-compile fast refusal and the authoritative post-compile
        check in _create_bucket_locked."""
        if len(self._buckets) < self.config.max_buckets:
            return
        if any(b.idle() for b in self._buckets[1:]):
            return
        self.admission_rejections += 1
        raise AdmissionError(
            f"no bucket headroom for signature {key.render()}: "
            f"{len(self._buckets)}/{self.config.max_buckets} "
            f"buckets busy; warm signatures this frontend can "
            f"serve cheaply: {self._warm_signatures()}")

    def _create_bucket_locked(self, key: SignatureKey,
                              engine: Engine) -> "_Bucket":
        if len(self._buckets) >= self.config.max_buckets:
            self._check_bucket_headroom_locked(key)
            victim = next((b for b in self._buckets[1:] if b.idle()), None)
            self._retire_bucket_locked(victim)
        b = _Bucket(self.config, engine.filter, key.op_chain, engine,
                    key=key)
        b._pooled = True  # leased through self.pool by _acquire_program
        if self.config.profile_dir:
            # One small JSON read at bucket creation (a path that just
            # paid a compile): a previous run's measured stage costs
            # seed the tick-cost estimate and the control annotations.
            b.stage_profile = load_stage_profile(
                self.config.profile_dir, key.render())
        self._buckets.append(b)
        self._bucket_by_key[key] = b
        if self.ledger is not None:
            self.ledger.record(ledger_mod.BUCKET_CREATE,
                               signature=key.render(),
                               bucket=key.render(),
                               open_buckets=len(self._buckets))
        return b

    def _retire_bucket_locked(self, bucket: "_Bucket") -> None:
        """Drop an idle bucket to make headroom. Its program is NOT
        compiled away — the pool lease drops, the program stays warm
        until LRU capacity pressure actually frees it, so a returning
        signature re-admits as a pool hit. Its host staging slabs ARE
        released eagerly: retired sessions keep a ``.bucket`` reference
        (for tail drains), so without this a churned bucket would pin
        2×(max_inflight+1) batch-sized buffers until its sessions age
        out of the retirement map."""
        self._buckets.remove(bucket)
        if bucket.key is not None:
            if self._bucket_by_key.get(bucket.key) is bucket:
                del self._bucket_by_key[bucket.key]
            if self.config.profile_dir:
                # Record (no disk I/O under this lock) so stop() still
                # persists a churned-out signature's measured costs.
                tick = bucket._tick_cost_ms
                if tick is None:
                    tick = getattr(bucket.engine, "step_block_ms", None)
                self._retired_bucket_costs[bucket.label()] = tick
            if getattr(bucket, "_pooled", False):
                self.pool.release(bucket.key)
        a, bucket.assembler = bucket.assembler, None
        f, bucket.fetcher = bucket.fetcher, None
        if a is not None:
            a.release()
        if f is not None:
            f.release()
        bucket.release_drained_fetchers()
        if self.ledger is not None:
            label = bucket.label()
            # A retired bucket never dispatches again: close out any
            # stall window it owned rather than let it dangle.
            self.ledger.abandon_stalls(label)
            self.ledger.record(ledger_mod.BUCKET_RETIRE, bucket=label,
                               signature=(bucket.key.render()
                                          if bucket.key is not None
                                          else None),
                               open_buckets=len(self._buckets))

    def _acquire_program(self, key: SignatureKey,
                         cause: str = ledger_mod.CAUSE_ADMISSION) -> Engine:
        """Lease (or AOT-compile) the program for ``key`` — the
        admission-time compile that replaces the first-frame JIT stall.
        ``cause`` labels the ledger/histogram record (admission /
        quality / precompile)."""
        def build() -> Engine:
            with self._lock:
                filt = self._filters_by_chain.get(key.op_chain)
            if filt is None:
                filt = build_filter(key.op_chain)
                if filt.stateful:
                    raise AdmissionError(
                        f"op_chain {key.op_chain!r} is stateful; a "
                        f"shared batch interleaves tenants, so temporal "
                        f"state would leak across sessions — stateless "
                        f"chains only")
                with self._lock:
                    self._filters_by_chain.setdefault(key.op_chain, filt)
            seed = None
            cal_sig = f"b{self.config.batch_size}|{key.render()}"
            if self.config.plan_cache_dir:
                # Warm-restart calibration seed (control.plan_cache): a
                # previous run on this exact (topology, batch signature)
                # already measured the H2D/D2H/step block costs — the
                # compile adopts them and skips its blocking measurement
                # passes (engine.calibration_seeded records the
                # adoption, and the ledgered compile's wall shows it).
                from dvf_tpu.control import plan_cache as _pc
                seed = _pc.load_calibrations(
                    self.config.plan_cache_dir,
                    self._topology_fingerprint(), cal_sig)
            eng = Engine(filt, mesh=self.engine.mesh,
                         chaos=self.config.chaos, op_chain=key.op_chain,
                         calibration_seed=seed)
            eng.compile((self.config.batch_size, *key.geometry),
                        key.np_dtype)
            if self.config.plan_cache_dir and not eng.calibration_seeded:
                from dvf_tpu.control import plan_cache as _pc
                _pc.save_calibrations(
                    self.config.plan_cache_dir,
                    self._topology_fingerprint(), cal_sig,
                    {"h2d_block_ms": eng.h2d_block_ms,
                     "d2h_block_ms": eng.d2h_block_ms,
                     "step_block_ms": eng.step_block_ms})
            return eng

        try:
            return self.pool.acquire(key, build, cause=cause)
        except AdmissionError:
            with self._lock:
                self.admission_rejections += 1
            raise
        except Exception as e:  # noqa: BLE001 — unknown op, bad
            # geometry for the filter, compile failure: all refusals at
            # the door, never a half-created bucket
            with self._lock:
                self.admission_rejections += 1
            raise AdmissionError(
                f"cannot compile program for signature {key.render()}: "
                f"{e!r}") from e

    def _warm_signatures(self) -> List[str]:
        """Signatures servable without a cold compile: pooled programs
        plus live pinned buckets (which may predate pool adoption).
        Lock-free (callers may hold the non-reentrant ``_lock``): the
        dict snapshot below is ``list(dict)`` — one C-level call, atomic
        under the GIL — so a concurrent open_stream insert cannot raise
        mid-iteration; at worst the list is one insert stale.
        """
        keys = {k.render() for k in self.pool.warm_keys()}
        keys.update(k.render() for k in list(self._bucket_by_key))
        return sorted(keys)

    def precompile(self, manifest: Any) -> List[str]:
        """Warm the program pool from a ``--precompile`` manifest
        (runtime.signature.parse_manifest): each signature compiles once
        here — populating the in-process pool AND the persistent
        compilation cache — then idles warm, so its first real admission
        is a pool hit. Returns the canonical signatures warmed."""
        warmed = []
        for entry in parse_manifest(manifest):
            key = entry["key"]
            self._acquire_program(key, cause=ledger_mod.CAUSE_PRECOMPILE)
            self.pool.release(key)  # stays warm, un-leased
            warmed.append(key.render())
        return warmed

    # -- auto-plan plane (dvf_tpu.control.planner / plan_cache) ----------

    def _topology_fingerprint(self) -> str:
        """Cached: what hardware this frontend drives, laid out how —
        the plan/calibration cache's invalidation axis."""
        if self._topology is None:
            from dvf_tpu.control.plan_cache import topology_fingerprint
            self._topology = topology_fingerprint(self.engine.mesh)
        return self._topology

    def _cal_signature(self, bucket: "_Bucket") -> Optional[str]:
        """The calibration-cache key for a bucket's compile: the batch
        size is part of the measured shape, so it is part of the key."""
        try:
            key = bucket.key or bucket.engine.signature_key
            if key is None:
                key = make_key(bucket.op_chain, bucket.frame_shape,
                               bucket.frame_dtype)
            return f"b{bucket.batch_size}|{key.render()}"
        except Exception:  # noqa: BLE001 — an unparseable display-name
            return None    #   chain just skips the calibration cache

    def _seed_calibrations(self, bucket: "_Bucket") -> None:
        """Before a bucket engine's FIRST compile: adopt the persisted
        (topology, batch signature) calibration triple from the plan
        cache so ``Engine.compile`` skips its blocking measurement
        passes on a warm restart. No cache dir, already compiled, or
        any cache miss → no-op (the cold path re-measures; always
        correct)."""
        eng = bucket.engine
        if (not self.config.plan_cache_dir
                or eng.calibration_seed is not None
                or eng.stats.compile_count > 0
                or bucket.frame_shape is None):
            return
        sig = self._cal_signature(bucket)
        if sig is None:
            return
        from dvf_tpu.control import plan_cache as _pc
        eng.calibration_seed = _pc.load_calibrations(
            self.config.plan_cache_dir, self._topology_fingerprint(), sig)

    def _save_calibrations(self, bucket: "_Bucket", before: int) -> None:
        """After a compile that actually MEASURED (ran here, was not
        seeded): persist the calibration triple so the next restart on
        this topology skips the measurement passes."""
        eng = bucket.engine
        if (not self.config.plan_cache_dir
                or eng.stats.compile_count == before
                or eng.calibration_seeded):
            return
        sig = self._cal_signature(bucket)
        if sig is None:
            return
        from dvf_tpu.control import plan_cache as _pc
        _pc.save_calibrations(
            self.config.plan_cache_dir, self._topology_fingerprint(), sig,
            {"h2d_block_ms": eng.h2d_block_ms,
             "d2h_block_ms": eng.d2h_block_ms,
             "step_block_ms": eng.step_block_ms})

    def autoplan(self, frame_shape, frame_dtype="uint8",
                 op_chain: Optional[str] = None,
                 log: Optional[Any] = None) -> Optional[dict]:
        """Plan this frontend's operating point for one signature —
        the auto-plan plane's entry point (``--autoplan`` on the CLI).
        Call AFTER :meth:`start` (the measured search pushes paced
        bursts through the live dispatch path).

        Warm restart: the cached winner for (canonical signature,
        geometry, topology fingerprint, planner version) applies in
        O(one JSON read) — no search, no traffic; the ledgered ``plan``
        event's ``wall_ms`` is the auditable "plan step under 50 ms"
        bound. Cold: the candidate grid is scored analytically from the
        compile-time calibration triple, the best ≤ 1/3 is
        live-profiled through a real measurement session (each
        candidate applied via the SAME actuators the controllers use —
        batch hot swap, tick write, depth-aware assembler rebuild), and
        the measured winner is applied, cached, and ledgered with its
        search cost. Returns the applied plan doc."""
        from dvf_tpu.control import planner as planner_mod

        t0 = time.perf_counter()
        say = log if log is not None else (lambda _m: None)
        chain = (self._buckets[0].op_chain if op_chain is None
                 else canonical_op_chain_or_verbatim(op_chain))
        key = make_key(chain, frame_shape, frame_dtype)
        signature = key.render()
        shape = tuple(key.geometry)
        topo = self._topology_fingerprint()
        cache_dir = self.config.plan_cache_dir
        plan = planner_mod.plan_from_cache(cache_dir, signature, shape,
                                           topo)
        if plan is not None:
            self._apply_plan(plan, reason="plan cache hit")
            wall = (time.perf_counter() - t0) * 1e3
            if self.ledger is not None:
                self.ledger.record(
                    ledger_mod.PLAN, cause=ledger_mod.CAUSE_AUTOPLAN,
                    signature=signature, cache="hit",
                    wall_ms=round(wall, 3), plan=plan.to_doc(),
                    topology=topo, legs=0, grid=0)
            say(f"autoplan: cache hit {plan.label()} ({wall:.1f} ms)")
            return plan.to_doc()
        base = planner_mod.Plan(
            batch_size=self.config.batch_size, tick_s=self.config.tick_s,
            ingest_depth=self.config.ingest_depth)
        # Quiesce the reactive loops for the search: the batch
        # controller would size the measurement bucket to its
        # occupancy of one, undoing every candidate's hot swap
        # mid-burst. Resumed after the winner's envelope is applied.
        if self.control_plane is not None:
            self.control_plane.paused = True
        try:
            sid = self.open_stream(op_chain=chain, frame_shape=shape,
                                   frame_dtype=key.dtype, tier=0,
                                   slo_ms=120000.0)
            frame = np.zeros(shape, dtype=key.np_dtype)
            try:
                # Warmup burst at the hand-set defaults: compiles the
                # program on the real serving path and measures (or
                # adopts from the calibration cache) the triple the
                # analytic pruner seeds from.
                warm = self._measure_plan_candidate(sid, frame, base)
                if "error" in warm:
                    raise ServeError(f"autoplan warmup failed: "
                                     f"{warm['error']}")
                with self._lock:
                    bucket = self._sessions[sid].bucket
                eng = bucket.engine
                cal = {"h2d_block_ms": eng.h2d_block_ms,
                       "d2h_block_ms": eng.d2h_block_ms,
                       "step_block_ms": eng.step_block_ms}
                # The hand-set batch is a starting guess, not a bound:
                # the grid probes up to 2x above it (whether a bigger
                # batch pays is exactly what measuring decides — the
                # analytic-only fleet path stays capped at the hand-set
                # batch because nothing measured says otherwise). The
                # winner becomes the envelope's ladder top.
                grid = planner_mod.candidate_grid(
                    batch_cap=2 * base.batch_size)
                def measure(p):
                    # Best-of-2: the first burst after a hot swap pays
                    # cold staging (fresh program, empty assembler
                    # ring) — the second burst is the steady state the
                    # plan will actually run at. Same repeat discipline
                    # as the bench table's A/B legs.
                    a = self._measure_plan_candidate(sid, frame, p)
                    if "error" in a:
                        return a
                    b = self._measure_plan_candidate(sid, frame, p)
                    return a if "error" in b or a["fps"] >= b["fps"] \
                        else b

                plan, comp = planner_mod.plan_search(
                    grid, measure,
                    cal=cal, cal_batch=base.batch_size,
                    stage_profile=bucket.stage_profile, log=log)
            except BaseException:
                # A failed search must not leave a half-applied
                # candidate driving the frontend: restore the hand-set
                # point.
                self.config.ingest_depth = base.ingest_depth
                self.set_tick_interval(base.tick_s)
                with self._lock:
                    s = self._sessions.get(sid)
                    b = s.bucket if s is not None else None
                if b is not None and b.batch_size != base.batch_size:
                    self.request_batch_size(b.label(), base.batch_size,
                                            reason="autoplan aborted")
                raise
            finally:
                self.close(sid, drain=False)
            self._apply_plan(plan, reason="measured plan search")
        finally:
            if self.control_plane is not None:
                self.control_plane.paused = False
        planner_mod.plan_to_cache(cache_dir, signature, shape, topo, plan)
        wall = (time.perf_counter() - t0) * 1e3
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.PLAN, cause=ledger_mod.CAUSE_AUTOPLAN,
                signature=signature, cache="miss",
                wall_ms=round(wall, 3), plan=plan.to_doc(),
                topology=topo, legs=plan.searched, grid=plan.grid,
                reason=f"winner {comp.get('winner')}")
        say(f"autoplan: live-profiled {plan.searched}/{plan.grid} -> "
            f"{plan.label()} ({wall:.0f} ms)")
        return plan.to_doc()

    def apply_plan_doc(self, doc: dict,
                       reason: Optional[str] = None) -> bool:
        """Apply an externally-chosen plan doc (the fleet front door
        plans once and pushes the winner to replicas). Returns False on
        an implausible doc — never raises over an optimization."""
        from dvf_tpu.control.planner import Plan

        plan = Plan.from_doc(doc)
        if plan is None:
            return False
        self._apply_plan(plan, reason=reason or "fleet plan")
        return True

    def _apply_plan(self, plan, reason: Optional[str] = None) -> None:
        """Make ``plan`` this frontend's operating point: the config
        fields (future buckets compile at the planned batch/depth), the
        live dispatch tick, every live bucket's batch size (hot swap
        when pinned, direct when nothing has flowed yet), and the
        control plane's operating envelope — the PR 10 reactive loops
        then adapt WITHIN the planned envelope (ladder bounded at the
        planned batch, planned tick as the busy tick) instead of
        rediscovering it from hard-coded defaults."""
        with self._lock:
            self.config.batch_size = plan.batch_size
            self.config.ingest_depth = plan.ingest_depth
            self.config.tick_s = plan.tick_s
            self.config.ingest = plan.ingest
            self.config.egress = plan.egress
            buckets = list(self._buckets)
        for b in buckets:
            with self._lock:
                unpinned = b.frame_shape is None
                if unpinned:
                    b.batch_size = plan.batch_size
                    b.ingest_mode = plan.ingest
                    b.egress_mode = plan.egress
            if not unpinned and b.batch_size != plan.batch_size:
                self.request_batch_size(b.label(), plan.batch_size,
                                        reason=reason or "autoplan")
        self.set_tick_interval(plan.tick_s)
        if self.control_plane is not None:
            self.control_plane.apply_envelope(plan.envelope(),
                                              reason=reason)
        self.applied_plan = plan.to_doc()

    def _measure_plan_candidate(self, sid: str, frame: np.ndarray,
                                plan) -> dict:
        """One candidate's live leg: apply its knobs through the REAL
        actuators (batch hot swap via :meth:`request_batch_size` — the
        same compile-aside path the controllers use — the tick write,
        and the ingest-depth config the next assembler rebuild picks
        up), then push a paced burst of ``autoplan_burst_frames``
        frames through the measurement session and report sustained
        fps. The row shape matches the bench table's A/B legs
        (``fps`` or ``error``), so `benchtools.ab_comparison` ranks
        the search — one shared paced-measurement path."""
        with self._lock:
            s = self._sessions.get(sid)
            bucket = s.bucket if s is not None else None
        if bucket is None:
            return {"error": f"measurement session {sid!r} gone"}
        self.config.ingest_depth = plan.ingest_depth
        self.set_tick_interval(plan.tick_s)
        if bucket.batch_size != plan.batch_size:
            with self._lock:
                if bucket.frame_shape is None:
                    bucket.batch_size = plan.batch_size
            if bucket.batch_size != plan.batch_size:
                self.request_batch_size(
                    bucket.label(), plan.batch_size,
                    reason=f"autoplan candidate {plan.label()}")
                deadline = time.time() + 30.0
                while bucket.batch_size != plan.batch_size:
                    if time.time() > deadline:
                        return {"error": f"hot swap to batch "
                                         f"{plan.batch_size} timed out"}
                    time.sleep(0.002)
        # Quiet the pipe first: a previous candidate's over-submitted
        # frames may still be IN FLIGHT (not just queued for poll), and
        # arriving mid-burst they would inflate this candidate's fps.
        # Wait until nothing has arrived for 50 ms before measuring.
        quiet_deadline = time.perf_counter() + 5.0
        last_arrival = time.perf_counter()
        while time.perf_counter() - last_arrival < 0.05:
            if self.poll(sid):
                last_arrival = time.perf_counter()
            if time.perf_counter() > quiet_deadline:
                break
            time.sleep(0.002)
        n = max(4, int(self.config.autoplan_burst_frames))
        # Paced: keep ~2 batches of standing work so batching engages,
        # but never more than the per-session ingress bound — a frame
        # dropped at ingress never delivers, which would read as a
        # stalled (infinitely slow) candidate instead of a paced one.
        backlog = max(2, min(2 * plan.batch_size, self.config.queue_size))
        delivered = in_flight = 0
        t0 = time.perf_counter()
        deadline = t0 + 60.0
        last_progress = t0
        while delivered < n:
            while in_flight < backlog:
                self.submit(sid, frame)
                in_flight += 1
            got = self.poll(sid)
            delivered += len(got)
            in_flight -= len(got)
            if got:
                last_progress = time.perf_counter()
                continue
            now = time.perf_counter()
            if now > deadline:
                return {"error": f"burst stalled at "
                                 f"{delivered}/{n} delivered"}
            if now - last_progress > 2.0:
                # A shed frame (drop-oldest racing a mid-burst resize
                # swap) never delivers; after 2 s of silence assume
                # the standing work evaporated and re-prime rather
                # than waiting out the deadline on ghosts. Throughput
                # stays honest — the clock keeps running and fps is
                # delivered-work over total wall.
                in_flight = 0
                last_progress = now
            time.sleep(0.001)
        wall = time.perf_counter() - t0
        return {"fps": round(n / wall, 2), "frames": n,
                "wall_s": round(wall, 4), "batch": plan.batch_size,
                "tick_s": plan.tick_s, "depth": plan.ingest_depth}

    # -- control-plane actuator surface (dvf_tpu.control) ----------------
    # The ControlPlane's apply thread calls these; the decisions behind
    # them are deterministic over the telemetry window (controllers.py).
    # Anything that must be serialized with staging (quality rebinds,
    # batch resizes) is handed to the dispatch thread instead of done
    # here — the apply thread only ever pays for COMPILES, never for a
    # lock the serving path is hot on.

    def control_view(self) -> dict:
        """The per-bucket/per-session half of a control row — what the
        plane composes with each flat telemetry sample before the
        controllers' decision step. Cheap: counter reads, no percentile
        work."""
        with self._lock:
            buckets = [(b, len(b.sessions),
                        sum(len(s.ingress) + len(s.pending)
                            for s in b.sessions.values()),
                        min((s.config.tier
                             for s in b.sessions.values()), default=None))
                       for b in self._buckets]
            sessions = list(self._sessions.items())
        b_rows = []
        for b, n_live, qd, min_tier in buckets:
            b_rows.append({
                "label": b.label(),
                "batch_size": b.batch_size,
                "queue_depth": qd,
                "open_sessions": n_live,
                "inflight_batches": b.inflight_batches,
                "mean_valid_rows": b.mean_valid_rows,
                "tick_cost_ms": b.tick_cost_estimate(),
                # Highest-priority tenant tier (the resize stall-guard:
                # a bucket hosting tier 0 never shrink-resizes).
                "min_tier": min_tier,
                # Measured mean per-component latency (live lineage
                # window, else the persisted stage profile): what the
                # controllers annotate their decisions with. None until
                # something has been measured.
                "stage_cost_ms": self._bucket_stage_cost(b),
            })
        s_rows = []
        for sid, s in sessions:
            s_rows.append({
                "sid": sid,
                "tier": s.config.tier,
                "level": s.quality_level,
                "downshiftable": self._downshiftable(s),
            })
        return {"buckets": b_rows, "sessions": s_rows}

    def _downshiftable(self, s: StreamSession) -> bool:
        """Whether one more ×2 downshift step is geometrically possible
        for this session (signature pinned, H and W divisible)."""
        sig = s.base_sig
        if sig is None:
            bucket = s.bucket if s.bucket is not None else self._buckets[0]
            sig = bucket.pinned_signature()
        if sig is None:
            return False
        shape = sig[0]
        f = 1 << (s.quality_level + 1)
        return len(shape) >= 2 and shape[0] % f == 0 and shape[1] % f == 0

    def request_batch_size(self, bucket_label: str, n: int,
                           reason: Optional[str] = None) -> bool:
        """Queue a per-bucket batch resize, served as a HOT SWAP: the
        dispatch thread kicks the new size's program compile to a
        background thread (through the pool and the persistent cache,
        so a previously-seen size costs a deserialize) while the bucket
        keeps serving at the old size, then commits the staged program
        with one pointer swing between ticks — no quiesce, no stall
        window. False = no such bucket (it retired between decide and
        apply). ``reason`` (the controller's decision rationale) rides
        into the ledger's ``swap`` event."""
        n = max(1, int(n))
        with self._lock:
            for b in self._buckets:
                if b.label() == bucket_label:
                    if n == b.batch_size:
                        self._pending_resizes.pop(b, None)
                    else:
                        self._pending_resizes[b] = (n, reason)
                    return True
        return False

    def set_tick_interval(self, tick_s: float) -> None:
        """The tick budget: how long dispatch idles between scheduling
        passes. Tight under load (queueing delay is paid per tick),
        relaxed when idle (a hot spin over empty queues is wasted
        host CPU)."""
        self._tick_s = max(1e-4, float(tick_s))

    def set_admission_tier_floor(self, floor: Optional[int]) -> None:
        """Controller-set admission floor: ``open_stream`` refuses
        sessions with tier > floor (None admits every tier)."""
        with self._lock:
            self._admission_tier_floor = floor

    def flight_trip(self, reason: str) -> None:
        """Control-plane observability tap (controller saturation):
        same off-thread flight dump as the watchdog/budget paths."""
        self._flight_trip(reason)

    def request_session_quality(self, session_id: str, level: int,
                                reason: Optional[str] = None) -> bool:
        """Move one session to quality ``level`` (0 = full). Builds or
        leases the downshift bucket's program HERE (apply thread — a
        compile must not stall sampling or dispatch), then hands the
        actual rebind to the dispatch thread, which owns the queues
        being flushed. False = impossible right now (session gone,
        geometry not divisible, bucket cap with no idle victim) — the
        controller counts it and re-decides on a later window."""
        level = int(level)
        if level < 0:
            return False
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None or s.state != OPEN:
                return False
            if level == s.quality_level:
                return True
            if s.base_sig is None:
                # First shift: capture the full-quality signature so
                # recovery can route home even if the base bucket
                # retires (its program stays warm in the pool).
                bucket = s.bucket if s.bucket is not None \
                    else self._buckets[0]
                pinned = bucket.pinned_signature()
                if pinned is None:
                    return False  # nothing has flowed yet — no geometry
                s.base_sig = pinned
                s.base_chain = bucket.op_chain
            shape, dtype = s.base_sig
            base_chain = s.base_chain
        key = self._quality_key(base_chain, shape, dtype, level)
        if key is None:
            return False
        try:
            self._ensure_quality_bucket(key, base_chain, level)
        except AdmissionError:
            return False
        self._pending_rebinds.put((session_id, key, level, reason, None))
        return True

    def morph_stream(self, session_id: str, op_chain: str,
                     reason: Optional[str] = None) -> bool:
        """Swap one live session's FILTER CHAIN mid-stream — no
        close/reopen, no index reset. The target chain's program is
        built or leased HERE (caller thread — a compile must not stall
        dispatch; through the pool it is usually a warm hit), then the
        cutover rides the rebind queue: the dispatch thread flushes the
        session's queued frames (old chain — they cannot enter the new
        program), swings the bucket binding between ticks, and ledgers
        a ``swap`` event (cause=morph) with the cutover frame index.
        Indices stay monotone: frames before the ledgered
        ``cutover_index`` were filtered by the old chain, frames at and
        after it by the new one. The adopted program carries a
        swap-guard equivalence verdict like every other substitution.
        False = impossible right now (session gone/closing, nothing
        flowed yet, malformed chain raises ServeError, bucket cap with
        no idle victim)."""
        try:
            chain = canonical_op_chain(op_chain)
        except Exception as e:  # noqa: BLE001 — surface as admission
            raise ServeError(f"morph_stream: bad op_chain "
                             f"{op_chain!r}: {e}") from None
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None or s.state != OPEN:
                return False
            if s.base_sig is None:
                bucket = s.bucket if s.bucket is not None \
                    else self._buckets[0]
                pinned = bucket.pinned_signature()
                if pinned is None:
                    return False  # nothing has flowed yet — no geometry
                s.base_sig = pinned
                s.base_chain = bucket.op_chain
            if chain == s.base_chain:
                return True  # already serving this chain
            shape, dtype = s.base_sig
            level = s.quality_level
        # The morph preserves the session's quality level: the target
        # key decimates the NEW chain at the same ladder rung.
        key = self._quality_key(chain, shape, dtype, level)
        if key is None:
            key = self._quality_key(chain, shape, dtype, 0)
            level = 0  # geometry stopped dividing under the new chain:
            #   morph to full quality rather than refuse the morph
        if key is None:
            return False
        try:
            self._ensure_quality_bucket(key, chain, level,
                                        cause=ledger_mod.CAUSE_MORPH)
        except AdmissionError:
            return False
        self._pending_rebinds.put((session_id, key, level, reason, chain))
        return True

    def _quality_key(self, base_chain: str, shape: tuple, dtype,
                     level: int) -> Optional[SignatureKey]:
        """The canonical signature serving ``base_chain`` at quality
        ``level``: decimated geometry + the matching upscale stage (so
        the program's OUTPUT stays full resolution). None when the
        geometry doesn't divide."""
        if level == 0:
            chain = base_chain
            geom = tuple(shape)
        else:
            f = 1 << level
            if len(shape) < 2 or shape[0] % f or shape[1] % f:
                return None
            chain = canonical_op_chain_or_verbatim(
                f"{base_chain}|upscale(scale={f})")
            geom = (shape[0] // f, shape[1] // f, *shape[2:])
        return SignatureKey(chain, canonical_geometry(geom),
                            canonical_dtype(dtype).name)

    def _warm_quality_async(self, bucket) -> None:
        """Pre-compile the ×2 downshift program for ``bucket``'s
        signature on a background thread (control armed only). The
        moment the quality controller needs that program is
        mid-overload — the worst possible time to pay a cold compile on
        a busy host — so it is warmed through the pool at ADMISSION
        time instead; the eventual downshift costs a pool hit. No-op
        for an unpinned bucket (an undeclared open warms once a later
        declared open or the running controller touches the bucket) and
        for an already-warm or live key."""
        if self.control_plane is None:
            return
        sig = bucket.pinned_signature()
        base_chain = bucket.op_chain
        if sig is None or base_chain is None:
            return
        shape, dtype = sig
        key = self._quality_key(base_chain, shape, dtype, 1)
        if key is None:
            return
        with self._lock:
            if key in self._warmed_quality \
                    or self._bucket_by_key.get(key) is not None:
                return
            self._warmed_quality.add(key)
            self._register_quality_chain_locked(key, base_chain, 2)

        def warm():
            try:
                self._acquire_program(key,
                                      cause=ledger_mod.CAUSE_QUALITY)
                self.pool.release(key)
            except Exception:  # noqa: BLE001 — a failed warm only means
                with self._lock:   # the first downshift pays the
                    self._warmed_quality.discard(key)   # compile after all

        threading.Thread(target=warm, name="dvf-quality-warm",
                         daemon=True).start()

    def _register_quality_chain_locked(self, key: SignatureKey,
                                       base_chain: str, scale: int) -> None:
        """Register the downshift chain's Filter under ``key.op_chain``
        (caller holds ``_lock``): the live base Filter composed with the
        matching ``upscale`` stage — needed when the base chain is an
        ad-hoc filter name ``build_filter`` can't re-parse. No-op when
        already registered or the base filter is unknown (a registry
        spec builds through ``_acquire_program`` instead)."""
        if key.op_chain in self._filters_by_chain:
            return
        base_filt = self._filters_by_chain.get(base_chain)
        if base_filt is not None:
            from dvf_tpu.ops import get_filter

            self._filters_by_chain[key.op_chain] = FilterChain(
                base_filt, get_filter("upscale", scale=scale),
                name=key.op_chain)

    def _ensure_quality_bucket(self, key: SignatureKey, base_chain: str,
                               level: int,
                               cause: str = ledger_mod.CAUSE_QUALITY
                               ) -> None:
        """Make a live bucket exist for ``key`` (join or create —
        open_stream's admission discipline, compile outside the lock).
        For a base chain that is NOT a registry spec (an ad-hoc filter
        name), the downshift filter is composed from the LIVE base
        Filter object instead of build_filter. ``cause`` labels the
        pool acquire in the ledger (quality rebind vs live morph)."""
        with self._lock:
            if self._bucket_by_key.get(key) is not None:
                return
            if level > 0:
                self._register_quality_chain_locked(key, base_chain,
                                                    1 << level)
            self._check_bucket_headroom_locked(key)
        engine = self._acquire_program(key, cause=cause)
        owned = False
        try:
            with self._lock:
                bucket = self._bucket_by_key.get(key)
                if bucket is None:
                    self._create_bucket_locked(key, engine)
                    owned = True
        finally:
            if not owned:
                self.pool.release(key)  # raced into existence: program
                #   stays warm, the live bucket keeps its own lease

    def _apply_rebinds_dispatch(self) -> None:
        """Dispatch-thread half of a quality move or a live morph:
        flush the session's queued frames (OLD geometry/chain — they
        cannot enter the new program), swap its bucket binding, set the
        level. Atomic with submit's decimate+enqueue under ``_lock``.
        The target bucket's program was compiled ASIDE before the
        request was queued (``_ensure_quality_bucket``), so the cutover
        here is one binding swing between ticks — no stall window is
        opened; the MEASURED swing duration is ledgered as the event's
        ``stall_ms`` (~0). A target bucket that retired between request
        and apply drops the move (counted); the controller re-decides
        from a later window."""
        while True:
            try:
                (sid, key, level, reason,
                 morph_chain) = self._pending_rebinds.get_nowait()
            except queue.Empty:
                return
            t_c = time.time()
            with self._lock:
                s = self._sessions.get(sid)
                if s is None or s.state == CLOSED:
                    self.quality_rebinds_dropped += 1
                    continue
                target = self._bucket_by_key.get(key)
                if target is None:
                    self.quality_rebinds_dropped += 1
                    continue
                old = s.bucket if s.bucket is not None else self._buckets[0]
                flushed = 0
                if target is not old:
                    flushed = s.flush_queued(count_shed=False)
                    self.quality_flushed_frames += flushed
                    old.sessions.pop(sid, None)
                    target.sessions[sid] = s
                    s.bucket = target
                if morph_chain is not None:
                    # Live morph: from here on the session's quality
                    # ladder decimates from the NEW chain; frame
                    # indices stay monotone (submitted is untouched).
                    s.base_chain = morph_chain
                    cutover = s.submitted
                    self.morphs += 1
                else:
                    s.quality_shifts += 1
                    self.quality_rebinds += 1
                s.quality_level = level
            stall_ms = round((time.time() - t_c) * 1e3, 3)
            if self.ledger is not None:
                if morph_chain is not None:
                    self.ledger.record(
                        ledger_mod.SWAP, cause=ledger_mod.CAUSE_MORPH,
                        signature=key.render(), bucket=target.label(),
                        session=sid, cutover_index=cutover,
                        frames_flushed=flushed, stall_ms=stall_ms,
                        reason=reason, t0=t_c)
                    self._observe_swap(stall_ms, key.render(),
                                       ledger_mod.CAUSE_MORPH)
                else:
                    # The rebind's tenant-visible cost is the MEASURED
                    # binding swing (the target program was compiled
                    # aside) — no stall window: the target bucket never
                    # stopped dispatching.
                    self.ledger.record(
                        ledger_mod.QUALITY_REBIND,
                        cause=ledger_mod.CAUSE_QUALITY,
                        signature=key.render(), bucket=target.label(),
                        session=sid, level=level, frames_flushed=flushed,
                        stall_ms=stall_ms, reason=reason, t0=t_c)
            if self.audit is not None:
                # Equivalence verdict for the program the session was
                # just rebound onto — vs the golden path of ITS OWN
                # chain: a rebind/morph is by design not equivalent to
                # the base program, but the substituted program must
                # still compute its chain. Async: this is the dispatch
                # thread — the probe runs on the audit worker (the
                # bucket keeps its engine leased; a raced retirement
                # yields probe_failed, not a crash).
                self.audit.swap_guard(
                    engine=target.engine, filt=target.filter,
                    kind="morph" if morph_chain is not None
                    else "quality_rebind",
                    cause=(ledger_mod.CAUSE_MORPH
                           if morph_chain is not None
                           else ledger_mod.CAUSE_QUALITY),
                    signature=key.render(), bucket=target.label(),
                    reason=reason, asynchronous=True)

    def _apply_resizes_dispatch(self) -> None:
        """Dispatch-thread half of a batch resize, hot-swap edition:
        kick the successor program's compile ASIDE on a short-lived
        background thread (``Engine.prepare_swap`` — through the
        persistent compilation cache, so a previously-seen size costs a
        deserialize) while the bucket KEEPS dispatching at the old
        size. When the aside-compile lands, the staged commit comes
        back through ``_pending_commits`` and
        :meth:`_apply_commits_dispatch` swings the program pointer
        between ticks — no quiesce, no stall window, in-flight batches
        on the old program drain and collect normally."""
        with self._lock:
            pending = list(self._pending_resizes.items())
        for bucket, (n, reason) in pending:
            with self._lock:
                # Liveness checked HERE, under the same lock that
                # retires buckets: a pre-loop snapshot could let a
                # just-retired bucket through, and its pooled engine —
                # possibly re-leased to a new bucket by now — would be
                # recompiled under a live tenant's feet.
                if bucket not in self._buckets:
                    self._pending_resizes.pop(bucket, None)
                    continue
                if bucket in self._preparing_swaps:
                    continue  # an aside-prepare is already in flight;
                    #   this (possibly newer) target waits its turn
                if self._pending_resizes.get(bucket) != (n, reason):
                    continue  # superseded since the snapshot above
                self._pending_resizes.pop(bucket, None)
                if bucket.frame_shape is None:
                    # Nothing has flowed yet: no program at the old size
                    # to swap, the first batch compiles at the new one.
                    bucket.batch_size = n
                    bucket.assembler = None
                    if self.ledger is not None:
                        self.ledger.record(
                            ledger_mod.BATCH_RESIZE,
                            cause=ledger_mod.CAUSE_RESIZE,
                            bucket=bucket.label(), batch_size=n,
                            wall_ms=0.0, reason=reason)
                    continue
                self._preparing_swaps.add(bucket)
                shape = (n, *bucket.frame_shape)
                dtype = np.dtype(bucket.frame_dtype)
            threading.Thread(
                target=self._swap_prepare_resize,
                args=(bucket, n, shape, dtype, reason),
                name="dvf-serve-swap-prepare", daemon=True).start()

    def _swap_prepare_resize(self, bucket: "_Bucket", n: int,
                             shape: tuple, dtype,
                             reason: Optional[str] = None) -> None:
        """Background half of a hot resize: capture the OLD program's
        probe row (the swap guard's bit-identity reference), compile
        the successor at the new batch shape aside, then hand the
        staged commit to the dispatch thread. A failed aside-compile
        is contained — the staged successor is discarded, the old
        program never stopped serving, and the abort is ledgered."""
        t0 = time.time()
        try:
            # Swap guard (obs.audit): the OLD program's probe output
            # captured BEFORE the swap can land — the resize
            # substitutes a program under live tenants, which is only
            # safe if equivalence is proven.
            old_row = (self.audit.probe_row(bucket.engine)
                       if self.audit is not None else None)
            with self._recover_lock:
                prep = bucket.engine.prepare_swap(shape, dtype)
        except Exception as e:  # noqa: BLE001 — counted, never raised
            with self._lock:                # into the serving path
                self.resize_compile_errors += 1
                self.swap_aborts += 1
                self._preparing_swaps.discard(bucket)
            if self.ledger is not None:
                self.ledger.record(
                    ledger_mod.SWAP, cause=ledger_mod.CAUSE_RESIZE,
                    bucket=bucket.label(), batch_size=n,
                    wall_ms=(time.time() - t0) * 1e3, aborted=True,
                    reason=f"aside compile failed (old program keeps "
                           f"serving): {e!r}", t0=t0)
            return
        self._pending_commits.put(
            ("resize", bucket, n, prep, old_row, reason, t0))

    def _apply_commits_dispatch(self) -> None:
        """Dispatch-thread commit of staged hot swaps: one pointer
        swing per swap, between ticks — the only serving time a swap
        consumes, measured and ledgered as its ``stall_ms``."""
        while True:
            try:
                item = self._pending_commits.get_nowait()
            except queue.Empty:
                return
            if item[0] == "resize":
                self._commit_resize_swap(*item[1:])

    def _commit_resize_swap(self, bucket: "_Bucket", n: int, prep: dict,
                            old_row, reason: Optional[str],
                            t0: float) -> None:
        with self._lock:
            live = bucket in self._buckets
            self._preparing_swaps.discard(bucket)
        if not live:
            bucket.engine.abort_swap()  # retired between prepare and
            return                      # commit: staging must not leak
        try:
            res = (bucket.engine.commit_swap()
                   if bucket.engine.swap_staged
                   else {"migrate_ms": 0.0, "stall_ms": 0.0,
                         "migrated": False})
        except Exception as e:  # noqa: BLE001 — abort contained: the
            #   old program is serving, untouched (commit_swap's
            #   failure contract); only the abort is ledgered
            with self._lock:
                self.resize_compile_errors += 1
                self.swap_aborts += 1
            if self.ledger is not None:
                self.ledger.record(
                    ledger_mod.SWAP, cause=ledger_mod.CAUSE_RESIZE,
                    bucket=bucket.label(), batch_size=n,
                    wall_ms=(time.time() - t0) * 1e3, aborted=True,
                    reason=f"swap commit failed (old program keeps "
                           f"serving): {e!r}", t0=t0)
            return
        self._adopt_bucket_key(bucket)  # takes self._lock itself
        with self._lock:
            bucket.batch_size = n
            bucket.assembler = None  # staging re-derives from the new
            #   program's sharding in _builder_for; the egress fetcher
            #   re-derives at the next dispatch (in-flight batches keep
            #   fetching through the fetcher pinned on their plan)
            self.swaps += 1
        if self.ledger is not None:
            label = bucket.label()
            self.ledger.record(
                ledger_mod.SWAP, cause=ledger_mod.CAUSE_RESIZE,
                signature=label, bucket=label, batch_size=n,
                wall_ms=(time.time() - t0) * 1e3,
                compile_aside_ms=round(
                    float(prep.get("compile_aside_ms", 0.0)), 3),
                migrate_ms=res["migrate_ms"],
                stall_ms=res["stall_ms"],
                cache=prep.get("cache"), reason=reason, t0=t0)
            self._observe_swap(res["stall_ms"], label,
                               ledger_mod.CAUSE_RESIZE)
        if self.audit is not None:
            # Equivalence verdict for the adopted program: probe
            # through the new program vs the golden path (and
            # bit-identity vs the old program's probe row — same
            # per-frame geometry across a batch resize). Async: this
            # is the dispatch thread. Ledgered as a swap_guard event:
            # zero unaudited substitutions.
            self.audit.swap_guard(
                engine=bucket.engine, filt=bucket.filter,
                kind="batch_resize", cause=ledger_mod.CAUSE_RESIZE,
                signature=bucket.label(), bucket=bucket.label(),
                old_row=old_row, reason=reason, asynchronous=True)

    def submit(self, session_id: str, frame: np.ndarray,
               ts: Optional[float] = None, tag: Any = None) -> int:
        """Enqueue one frame on a stream; returns its per-stream index."""
        if self._error is not None:
            # The service threads died (error budget exhausted / fail-fast
            # fault): surface it to the submitting client instead of
            # queueing frames nothing will ever serve.
            raise ServeError(
                f"frontend failed: {self._error!r}") from self._error
        s = self._session(session_id)
        if self.control_plane is None:
            # No control plane → no quality rebinds: a session's bucket
            # binding and level are fixed after open, so the hot path
            # stays lock-free (the lock below exists only to serialize
            # with rebind flushes). Geometry pin is the one first-frame
            # race, double-checked under the lock.
            bucket = s.bucket if s.bucket is not None else self._buckets[0]
            if bucket.frame_shape is None:
                with self._lock:
                    if bucket.frame_shape is None:
                        bucket.frame_shape = tuple(frame.shape)
                        bucket.frame_dtype = np.dtype(frame.dtype)
            if tuple(frame.shape) != tuple(bucket.frame_shape) \
                    or np.dtype(frame.dtype) != np.dtype(
                        bucket.frame_dtype):
                raise ValueError(
                    f"frame {frame.shape}/{frame.dtype} does not match "
                    f"this stream's pinned signature "
                    f"{tuple(bucket.frame_shape)}/"
                    f"{np.dtype(bucket.frame_dtype)} (one compiled "
                    f"program serves every session in a bucket — "
                    f"geometry is per-bucket, not per-stream; open a "
                    f"stream with frame_shape=/op_chain= to route to "
                    f"another bucket)")
            return s.submit(frame, ts=ts, tag=tag)
        # ONE atomic section for the (bucket, quality_level) read, the
        # decimation, the geometry check, AND the enqueue: quality
        # rebinds (dispatch thread) swap bucket+level and flush the
        # queues under this same lock, so no frame of the OLD geometry
        # can slip into the ingress after the flush — without this, a
        # submit racing a rebind could poison a whole device batch.
        with self._lock:
            bucket = s.bucket if s.bucket is not None else self._buckets[0]
            level = s.quality_level
            if level > 0:
                # Downshifted session: decimate ×2^level per axis at the
                # door (a strided VIEW — zero copy until staging); the
                # downshift bucket's op chain ends in the matching
                # upscale stage, so the DELIVERY is still full
                # resolution. Bit-exactness is waived exactly while the
                # level is > 0.
                f = 1 << level
                frame = frame[::f, ::f]
            if bucket.frame_shape is None:
                bucket.frame_shape = tuple(frame.shape)
                bucket.frame_dtype = np.dtype(frame.dtype)
            if tuple(frame.shape) != tuple(bucket.frame_shape) \
                    or np.dtype(frame.dtype) != np.dtype(bucket.frame_dtype):
                raise ValueError(
                    f"frame {frame.shape}/{frame.dtype} does not match this "
                    f"stream's pinned signature {tuple(bucket.frame_shape)}/"
                    f"{np.dtype(bucket.frame_dtype)} (one compiled program "
                    f"serves every session in a bucket — geometry is "
                    f"per-bucket, not per-stream; open a stream with "
                    f"frame_shape=/op_chain= to route to another bucket)")
            return s.submit(frame, ts=ts, tag=tag)

    def poll(self, session_id: str, max_items: Optional[int] = None) -> list:
        """Pop completed ``Delivery`` records for one stream (works on
        retired sessions until their tail is drained)."""
        return self._session(session_id).poll(max_items)

    def resume_token(self, session_id: str) -> str:
        """A resume credential for an open (or retired-but-pollable)
        session: a keyed MAC over the session id, verified by
        :meth:`resume_stream`. Cheap and stateless — issue it at open
        time and hand it to the client beside the session id."""
        self._session(session_id)  # existence check (raises KeyError)
        return make_resume_token(session_id, 0, self._token_secret)

    def resume_stream(self, session_id: str, token: str,
                      from_index: int = 0) -> list:
        """Replay the session's retained delivered tail from
        ``from_index`` (inclusive) — the reconnect path.

        Returns the replayed ``Delivery`` records in index order; the
        caller dedups by index against what it already has (duplicates
        are EXPECTED — replay overlaps the frames that did arrive).
        Frames older than the replay window are gone (the ring is
        bounded); a client that reconnects within the window gets an
        exactly-once stream, one that waited longer sees a gap it must
        treat as at-most-once loss. Raises ``ServeError`` on a bad
        token (counted as ``resume_rejected``), ``KeyError`` on an
        unknown session."""
        if check_resume_token(token, session_id, self._token_secret) is None:
            self.continuity.inc("resume_rejected")
            raise ServeError(
                f"invalid resume token for session {session_id!r}")
        s = self._session(session_id)
        replayed = ([] if s.replay is None
                    else [d for _, d in s.replay.replay_from(from_index)])
        self.continuity.inc("resumes")
        self.continuity.inc("replays")
        self.continuity.inc("replayed_frames", len(replayed))
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.RESUME, cause=ledger_mod.CAUSE_RECOVERY,
                sid=session_id, from_index=int(from_index),
                replayed=len(replayed))
        return replayed

    def close(self, session_id: str, drain: bool = True) -> None:
        """Per-session teardown. ``drain=True`` (graceful) serves what's
        queued and in flight first; the dispatch thread retires the
        session once it has drained. Other sessions are untouched."""
        self._session(session_id).close(drain=drain)

    def open_count(self) -> int:
        """Number of non-retired sessions — cheap (no percentile work),
        for polling loops that just watch for drain/retirement."""
        with self._lock:
            return len(self._sessions)

    def release(self, session_id: str) -> None:
        """Forget a retired session (its undrained tail is dropped).
        Call once the client has polled everything it wants — retired
        sessions are otherwise only evicted by the max_retired bound."""
        with self._lock:
            if session_id in self._sessions:
                raise ServeError(
                    f"session {session_id!r} is still open; close() it first")
            s = self._retired.pop(session_id, None)
            if s is not None:
                self._absorb_totals_locked(s)

    def _session(self, session_id: str) -> StreamSession:
        with self._lock:
            s = self._sessions.get(session_id) or self._retired.get(session_id)
        if s is None:
            raise KeyError(f"unknown session {session_id!r}")
        return s

    def _absorb_totals_locked(self, s: StreamSession) -> None:
        """Fold a session leaving the retired map into the lifetime
        counter floor (see _evicted_totals)."""
        t = self._evicted_totals
        t["submitted"] += s.submitted
        t["delivered"] += s.delivered
        t["shed"] += s.shed
        t["slo_miss"] += s.slo_miss
        t["failed"] += s.failed
        t["dropped_at_ingress"] += s.ingress.dropped

    def _retire_locked(self, sid: str, session: StreamSession) -> None:
        """Move one session to the retired map, evicting oldest beyond
        the retention bound (dicts iterate in insertion order)."""
        self._retired[sid] = session
        while len(self._retired) > self.config.max_retired:
            self._absorb_totals_locked(
                self._retired.pop(next(iter(self._retired))))

    # -- service threads -------------------------------------------------

    def _builder_for(self, bucket: "_Bucket", seq: int):
        """One staged batch via the bucket's assembler (runtime/ingest.py)
        — both ingest modes; the assembler owns the per-inflight-slot
        staging pool (max_inflight + 1 buffers: the one being rewritten
        always belongs to an already-collected batch, exactly like the
        single-stream pipeline's). Per bucket because the slab layout
        derives from THAT bucket's compiled input sharding AND its
        (control-plane-resizable) batch size."""
        shape = (bucket.batch_size, *bucket.frame_shape)
        dtype = np.dtype(bucket.frame_dtype)
        if (bucket.assembler is None
                or bucket.assembler.batch_shape != shape
                or bucket.assembler.depth != self.config.ingest_depth):
            # The depth check is the auto-plan seam: a planned (or
            # candidate) ingest depth lands in config and the next
            # rebuild picks it up — exactly how a batch resize already
            # re-derives the slab layout.
            before = bucket.engine.stats.compile_count
            self._seed_calibrations(bucket)
            bucket.engine.ensure_compiled(shape, dtype)
            self._save_calibrations(bucket, before)
            # A compile that actually ran here is the legacy lazy pin
            # (default bucket, first traffic) — ledger it as an
            # admission-cause compile ON THE DISPATCH THREAD, which is
            # exactly the JIT stall the AOT path exists to avoid.
            self._record_inline_compile(bucket, before,
                                        ledger_mod.CAUSE_ADMISSION)
            self._adopt_bucket_key(bucket)
            bucket.ingest_stats = IngestStats(
                requested_mode=self.config.ingest,
                depth=self.config.ingest_depth,
                h2d_block_ms=bucket.engine.h2d_block_ms)
            bucket.assembler = ShardedBatchAssembler(
                shape, dtype, bucket.engine.input_sharding,
                mode=bucket.ingest_mode, depth=self.config.ingest_depth,
                slots=self.config.max_inflight + 1,
                stats=bucket.ingest_stats, chaos=self.config.chaos,
                tracer=self.tracer, track=TRACK_H2D)
            if bucket.degrade_reason is not None:
                bucket.ingest_stats.fallback_reason = bucket.degrade_reason
        return bucket.assembler.begin(seq)

    def _adopt_bucket_key(self, bucket: "_Bucket") -> None:
        """Once a bucket's engine has compiled, its canonical signature
        is known: register the bucket under it (a later declared open of
        the same signature joins this bucket instead of forking a
        duplicate program) and adopt the engine into the program pool
        (the signature stays warm after the bucket retires)."""
        if getattr(bucket, "_pooled", False):
            return
        key = bucket.engine.signature_key
        if key is None:
            return
        prof = (load_stage_profile(self.config.profile_dir, key.render())
                if self.config.profile_dir else None)
        with self._lock:
            if bucket.key is None:
                bucket.key = key
            if prof is not None and bucket.stage_profile is None:
                bucket.stage_profile = prof
            self._bucket_by_key.setdefault(key, bucket)
        try:
            self.pool.adopt(key, bucket.engine)
        except (ValueError, RuntimeError):
            return  # another engine already pooled under this key (or
            #   the pool closed mid-stop): this engine stays un-pooled;
            #   stop() frees it directly
        bucket._pooled = True

    def _fetcher_for(self, bucket: "_Bucket"):
        """The bucket's streamed-egress fetcher for its engine's
        compiled output signature — the delivery-side mirror of
        ``_builder_for``, same slot discipline (max_inflight + 1 slabs;
        the router copies rows out during route(), so a slab is
        quiescent before its slot cycles). Built by the dispatch thread;
        the collect thread only reads it."""
        shape = getattr(bucket.engine, "out_shape", None)
        if shape is None:
            return None
        f = bucket.fetcher
        if f is None or f.out_shape != tuple(shape):
            if f is not None:
                # Output signature changed under a hot swap: batches
                # already prefetched into the old fetcher are still in
                # flight (their plans pin it) — park it for release
                # once the bucket's window drains instead of freeing
                # slabs the collect side is about to read.
                bucket.draining_fetchers.append(f)
            bucket.egress_stats = EgressStats(
                requested_mode=self.config.egress,
                d2h_block_ms=bucket.engine.d2h_block_ms)
            bucket.fetcher = f = ShardedBatchFetcher(
                shape, bucket.engine.out_dtype,
                bucket.engine.output_sharding,
                mode=bucket.egress_mode,
                slots=self.config.max_inflight + 1,
                stats=bucket.egress_stats, chaos=self.config.chaos,
                tracer=self.tracer, track=TRACK_D2H)
            if bucket.egress_degrade_reason is not None:
                bucket.egress_stats.fallback_reason = \
                    bucket.egress_degrade_reason
        return f

    def _fail(self, e: BaseException) -> None:
        first = self._error is None
        if first:
            self._error = e
        self._stop.set()
        if first:
            # Hard failure (fault budget exhausted, fail-fast fault,
            # unrecoverable engine): the exact moment a post-mortem is
            # worth a dump. Best-effort, rate-limited in the recorder.
            self._flight_trip(f"frontend failed: {e!r}")

    def _contain(self, e: BaseException, where: str,
                 bucket: Optional["_Bucket"] = None) -> bool:
        """Bounded containment (resilience.budget): classify, count,
        continue while within the per-kind budget; the first overflow
        degrades (h2d → monolithic ingest, compute/oom → supervised
        engine rebuild), the second surfaces a hard ServeError — a
        permanently broken engine must not serve 0 fps silently.
        Budgets attribute PER BUCKET: one signature's broken program
        spends its own budget, never another tenant mix's."""
        kind = classify(e, site=where)
        self.faults.record(kind, e)
        if bucket is not None:
            bucket.record_fault(kind)
        if not (self.config.resilient and isinstance(e, Exception)):
            self._fail(e)
            return False
        self.errors += 1
        budget = bucket.budget if bucket is not None else self._budget
        if escalate(budget, kind,
                    lambda k: self._degrade(k, bucket)) == ErrorBudget.CONTAIN:
            print(f"[serve:{where}] {kind} fault (continuing): {e!r}",
                  file=sys.stderr, flush=True)
            return True
        self._fail(ServeError(
            f"error budget exhausted for {kind!r} faults "
            f"(> {self.config.fault_budget} in "
            f"{self.config.fault_window_s:g}s, after degradation"
            + (f"; bucket {bucket.label()}" if bucket is not None else "")
            + f"); last: {e!r}"))
        return False

    def _degrade(self, kind: str,
                 bucket: Optional["_Bucket"] = None) -> bool:
        """First-overflow degradation per kind (per bucket). Returns
        True if applied (the fault is then still contained; a second
        overflow fails)."""
        b = bucket if bucket is not None else self._buckets[0]
        if kind == FaultKind.H2D and b.ingest_mode == "streamed":
            b.ingest_mode = "monolithic"
            b.degrade_reason = "h2d_fault_budget"
            b.assembler = None
            print(f"[serve] repeated h2d faults: degrading ingest "
                  f"streamed → monolithic (bucket {b.label()})",
                  file=sys.stderr, flush=True)
            return True
        if kind == FaultKind.D2H and b.egress_mode == "streamed":
            b.egress_mode = "monolithic"
            b.egress_degrade_reason = "d2h_fault_budget"
            old, b.fetcher = b.fetcher, None
            if old is not None:
                old.release()
            print(f"[serve] repeated d2h faults: degrading egress "
                  f"streamed → monolithic (bucket {b.label()})",
                  file=sys.stderr, flush=True)
            return True
        if kind in (FaultKind.COMPUTE, FaultKind.OOM, FaultKind.INTERNAL):
            # The bucket's engine itself may be the broken thing
            # (poisoned compile cache, leaked device state): rebuild it
            # once. If the fresh engine still faults through a second
            # budget window, the filter/input is broken, not the
            # engine — FAIL.
            self._recover(f"fault budget overflow ({kind})", kind=kind,
                          bucket=b)
            return True
        return False

    def _on_stall(self, reason: str) -> None:
        """Watchdog callback (supervisor thread): a submitted batch aged
        past stall_timeout_s without materializing."""
        e = FaultError(FaultKind.STALL, f"serve stalled: {reason}")
        self.faults.record(FaultKind.STALL, e)
        if not self.config.resilient:
            self._fail(e)
            return
        self.errors += 1
        # Stall escalation is consecutive, not time-windowed: stalls
        # arrive at most once per stall_timeout_s, so a sliding window
        # could never fill — instead, recoveries that never restore
        # service (no batch materializes in between, which would reset
        # the counter in _collect) declare the engine unrecoverable.
        self._stalls_since_progress += 1
        if self._stalls_since_progress > self._stall_fail_after:
            self._fail(ServeError(
                f"{self._stalls_since_progress} consecutive stall "
                f"recoveries without a served batch (engine "
                f"unrecoverable): {reason}"))
            return
        self._recover(reason, kind=FaultKind.STALL)

    def _recover(self, reason: str, kind: str = FaultKind.STALL,
                 bucket: Optional["_Bucket"] = None) -> None:
        """Supervised recovery: shed the in-flight window (each lost
        frame attributed to ``kind`` in its session's fault counters),
        replace the collect thread (a wedged one exits when it wakes —
        generation check), rebuild the affected buckets' Engines
        (recompile, re-warm, re-calibrate h2d_block_ms — through the
        program pool, so the persistent cache absorbs the recompile),
        and reset the in-flight semaphore. ``bucket`` names the faulted
        bucket when the caller knows it (budget overflow); a stall
        rebuilds every bucket found in the shed window (all buckets if
        the window was empty — the wedge has no known owner). Open
        sessions are untouched: their frame index spaces, reorder
        cursors, and out queues survive, so indices stay monotone across
        the recovery. Runs in whichever thread detected the fault
        (supervisor, dispatch, or collect); serialized by _recover_lock.
        """
        with self._recover_lock:
            if self._stop.is_set():
                return
            print(f"[serve] recovering engine ({reason}): shedding "
                  f"in-flight window, rebuilding engine",
                  file=sys.stderr, flush=True)
            self._recovering.set()
            affected = set() if bucket is None else {bucket}
            try:
                # Wait (bounded) for the dispatch thread to park, unless
                # WE are the dispatch thread (then it's here, not mid-
                # staging): a straddling iteration could otherwise put a
                # batch into the old queue after the drain below. If it's
                # wedged past the deadline, any straggler is caught by
                # the watchdog window on the next trip.
                if threading.current_thread() is not self._dispatch_thread:
                    deadline = time.monotonic() + 2.0
                    while (not self._dispatch_parked.is_set()
                           and not self._stop.is_set()
                           and time.monotonic() < deadline):
                        time.sleep(0.002)
                old_q = self._inflight
                while True:  # shed everything queued for collection
                    try:
                        seq, plan, _result, _t0 = old_q.get_nowait()
                    except queue.Empty:
                        break
                    if plan.bucket is not None:
                        affected.add(plan.bucket)
                    self.router.discard(plan, kind=kind)
                    self._window.remove(seq)
                # Batches popped by a wedged collect but never routed:
                # write them off too (route() skips dead plans if that
                # thread ever wakes up holding one). The window is owned
                # by the frontend, so this works with the watchdog off.
                for _seq, plan in self._window.drain():
                    if plan is not None:
                        if plan.bucket is not None:
                            affected.add(plan.bucket)
                        self.router.discard(plan, kind=kind)
                # Fresh queue + semaphore BEFORE the replacement collect
                # thread starts: generation-pinning means the old thread
                # only ever sees the old (now drained) queue, and permits
                # held by shed batches die with the old semaphore instead
                # of leaking into (or over-crediting) the new window.
                self._inflight = queue.Queue()
                self._inflight_sem = threading.Semaphore(
                    self.config.max_inflight)
                # Replace the collect thread; a live one exits at its next
                # generation check, a wedged one whenever it wakes. Prune
                # exited threads first — a long-lived server recovering
                # through intermittent fault bursts must not accumulate
                # one dead Thread per recovery forever.
                self._collect_gen += 1
                t = threading.Thread(
                    target=self._collect, name="dvf-serve-collect",
                    daemon=True, args=(self._collect_gen,))
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
                t.start()
                # Rebuild the affected buckets' engines. A wedge with no
                # known owner (empty window, no named bucket) rebuilds
                # everything — correctness first; the persistent cache
                # makes the recompiles deserializes, not fresh XLA runs.
                with self._lock:
                    all_buckets = list(self._buckets)
                targets = affected or set(all_buckets)
                for b in targets:
                    t_rb = time.time()
                    stall_from = (b.last_dispatch_t
                                  if b.last_dispatch_t is not None
                                  else t_rb)
                    # Swap guard: old-program probe BEFORE the rebuild
                    # replaces it (best-effort — a broken engine's
                    # probe failing is itself expected here).
                    old_row = (self.audit.probe_row(b.engine)
                               if self.audit is not None else None)
                    swapped = False
                    sig = b.engine.signature
                    if sig is not None:
                        # Double-buffered rebuild: compile the fresh
                        # program aside, then adopt it in place —
                        # Engine identity stays stable, so pool leases
                        # (and any other bucket sharing the lease)
                        # survive without pool.replace. force=True:
                        # the live program is suspect, a same-signature
                        # short-circuit would hand it right back.
                        # migrate_state=False: suspect state must not
                        # be carried into the replacement.
                        shape, dtype = sig
                        try:
                            b.engine.prepare_swap(shape, dtype,
                                                  force=True)
                            b.engine.commit_swap(migrate_state=False)
                            swapped = True
                            self.swaps += 1
                        except Exception:  # noqa: BLE001 — fall back
                            b.engine.abort_swap()   # to the cold path
                    if not swapped:
                        b.engine = b.engine.rebuild()
                        if b._pooled and b.key is not None:
                            try:
                                self.pool.replace(b.key, b.engine)
                            except RuntimeError:
                                # Pool closed mid-recovery (owner
                                # stopping): replace() freed the rebuilt
                                # engine — the frontend is past serving
                                # this bucket.
                                pass
                    a, b.assembler = b.assembler, None
                    f, b.fetcher = b.fetcher, None  # re-derive from the
                    #   fresh engine's re-calibrated d2h_block_ms; slabs
                    #   released eagerly so the memory accounting never
                    #   counts an abandoned pool as occupied
                    if a is not None:
                        a.release()
                    if f is not None:
                        f.release()
                    b.release_drained_fetchers()  # window fully shed:
                    #   nothing in flight can still pin them
                    if self.ledger is not None:
                        label = b.label()
                        compile_ms = b.engine.last_compile_ms
                        self.ledger.record(
                            ledger_mod.ENGINE_REBUILD,
                            cause=ledger_mod.CAUSE_RECOVERY,
                            signature=label, bucket=label,
                            fault_kind=kind, reason=reason,
                            wall_ms=(time.time() - t_rb) * 1e3,
                            compile_ms=(round(float(compile_ms), 3)
                                        if compile_ms is not None
                                        else None),
                            swap=swapped or None,
                            t0=t_rb, stall_from=stall_from)
                        if compile_ms is not None:
                            self._observe_compile(
                                compile_ms, label,
                                ledger_mod.CAUSE_RECOVERY)
                    if self.audit is not None:
                        # Equivalence verdict for the rebuilt program
                        # (recovery substitutes it under live
                        # sessions): new probe vs golden, plus
                        # bit-identity vs the old program when it was
                        # still probeable.
                        self.audit.swap_guard(
                            engine=b.engine, filt=b.filter,
                            kind="engine_rebuild",
                            cause=ledger_mod.CAUSE_RECOVERY,
                            signature=b.label(), bucket=b.label(),
                            old_row=old_row, reason=reason)
                # Second straggler sweep: a dispatch iteration that was
                # mid-staging when the drain above ran (wedged past the
                # park deadline) has had the whole engine rebuild to land
                # its put into the abandoned queue/window — write it off
                # now so its sessions' claims never leak even with the
                # watchdog (whose next trip would otherwise catch it) off.
                while True:
                    try:
                        seq, plan, _result, _t0 = old_q.get_nowait()
                    except queue.Empty:
                        break
                    self.router.discard(plan, kind=kind)
                    self._window.remove(seq)
                for _seq, plan in self._window.drain():
                    if plan is not None:
                        self.router.discard(plan, kind=kind)
                # The window is empty: no bucket has anything in flight.
                for b in all_buckets:
                    b.reset_inflight()
                self.recoveries += 1
            finally:
                self._recovering.clear()

    def _finalize_drained(self) -> None:
        """Retire closing sessions with nothing left queued or in flight
        (dispatch thread — it owns the pending deques being checked)."""
        with self._lock:
            done = [(sid, s) for sid, s in self._sessions.items()
                    if s.drained()]
            for sid, s in done:
                self._sessions.pop(sid)
                if s.bucket is not None:
                    s.bucket.sessions.pop(sid, None)
                self._retire_locked(sid, s)
        for _, s in done:
            s.finalize()

    def _dispatch(self) -> None:
        seq = 0
        try:
            while not self._stop.is_set():
                if self._recovering.is_set():
                    # Supervised recovery in progress: park — the engine,
                    # queue, and semaphore are being replaced under us.
                    # _recover waits for this flag before touching them.
                    self._dispatch_parked.set()
                    time.sleep(self._tick_s)
                    continue
                self._dispatch_parked.clear()
                if self._supervisor is not None:
                    self._supervisor.beat("dispatch")
                # Control-plane actuations owned by THIS thread: quality
                # rebinds / morphs (flush + bucket swap touch the
                # session pending deques only dispatch may touch),
                # batch-resize aside-prepares (kicked to a background
                # thread; the bucket keeps serving), and staged swap
                # commits (the atomic pointer swing between ticks).
                if not self._pending_rebinds.empty():
                    self._apply_rebinds_dispatch()
                if self._pending_resizes:
                    self._apply_resizes_dispatch()
                if not self._pending_commits.empty():
                    # Staged hot swaps land HERE, between ticks: one
                    # pointer swing per swap — the only serving time a
                    # reconfiguration consumes on this thread.
                    self._apply_commits_dispatch()
                with self._lock:
                    # Buckets with an aside-prepare in flight keep
                    # dispatching at the OLD size/program — a hot swap
                    # never quiesces; the commit lands between ticks.
                    bucket_sessions = [
                        (b, [s for s in b.sessions.values()
                             if s.state != CLOSED])
                        for b in self._buckets if b.sessions]
                plan = None
                if bucket_sessions:
                    # One bucket per tick (one compiled program per
                    # batch): EDF-headroom ÷ measured tick cost picks
                    # the bucket, then the ordinary within-bucket EDF
                    # picks the slots. Frames are staged through the
                    # bucket's assembler below, after the in-flight
                    # permit is acquired (the permit is what makes
                    # staging-slab reuse safe) — one staging
                    # implementation for both ingest modes.
                    pick, chosen = self.batcher.select_bucket(
                        bucket_sessions, time.time())
                    if chosen:
                        plan = BatchPlan(batch=None, valid=len(chosen),
                                         slots=chosen, bucket=pick)
                self._finalize_drained()
                if plan is None:
                    time.sleep(self._tick_s)
                    continue
                # Bounded in-flight depth; poll so shutdown can't wedge on
                # a dead collect thread. Acquired before any staging
                # buffer is touched — the permit is what makes
                # staging/slab reuse safe. The semaphore AND queue are
                # captured per iteration: recovery installs fresh ones,
                # and a batch must live entirely in one generation — a
                # straddler releasing a permit into the NEW semaphore
                # would over-credit the window (one extra batch in flight
                # breaks the staging pool's max_inflight+1 reuse contract).
                sem = self._inflight_sem
                acquired = False
                while True:
                    if sem.acquire(timeout=0.1):
                        acquired = True
                        break
                    if self._stop.is_set():
                        self.router.discard(plan)
                        return
                    if self._recovering.is_set():
                        break  # shed below, then park at the loop top
                    sem = self._inflight_sem
                if not acquired or sem is not self._inflight_sem:
                    # Recovery started while we waited (or swapped the
                    # semaphore right after our acquire): shed this plan
                    # into the recovery's accounting rather than staging
                    # into structures being torn down.
                    self.router.discard(plan, kind=FaultKind.STALL)
                    continue
                q = self._inflight
                t0 = time.time()
                bucket = plan.bucket
                if self.attribution is not None:
                    # Lineage hop: bucket queue wait ends as staging
                    # begins (one stamp per batch, fanned to the chosen
                    # slots); the batch-level marks list then collects
                    # assemble_h2d here and device/d2h on the collect
                    # side — the router extends each slot's lineage.
                    for slot in plan.slots:
                        if slot.lin is not None:
                            slot.lin.mark("queue_bucket", t0)
                    plan.lin_marks = []
                # A tick-cost sample is trustworthy only when nothing
                # else is in flight at submit: otherwise submit→
                # materialize includes queue wait behind OTHER batches'
                # device time (possibly other buckets' much costlier
                # programs) and the EWMA the EDF/cost score divides by
                # converges to the shared pipeline latency, not this
                # program's cost. Contended ticks still count batches;
                # they just don't feed the estimate.
                plan.cost_sample = len(self._window) == 0
                if self.audit is not None:
                    # Shadow-replay sampling (obs.audit): the sampler
                    # decides per staged frame; a picked frame's INPUT
                    # is copied here — the only place it still exists —
                    # and paired with its delivered output at collect.
                    # One modulo per frame when nothing is picked.
                    for row, slot in enumerate(plan.slots[: plan.valid]):
                        if self.audit.want_sample():
                            if plan.audit_rows is None:
                                plan.audit_rows = []
                            plan.audit_rows.append(
                                (row, np.array(slot.frame, copy=True),
                                 slot.session.id, slot.index, slot.lin))
                try:
                    builder = self._builder_for(bucket, seq)
                    for row, slot in enumerate(plan.slots):
                        builder.write_row(row, slot.frame)
                        slot.frame = None  # drop the client's buffer
                    batch, resident = builder.finish(plan.valid)
                    engine = bucket.engine
                    result = (engine.submit_resident(batch)
                              if resident else engine.submit(batch))
                    if plan.lin_marks is not None:
                        # Batch assembly + H2D ends at submit return
                        # (async dispatch: the device now owns the batch).
                        plan.lin_marks.append(("assemble_h2d", time.time()))
                    # Start the D2H now — per output shard on the streamed
                    # egress path — so the collect side only waits, never
                    # initiates (runtime/egress.py).
                    fetcher = self._fetcher_for(bucket)
                    if fetcher is not None:
                        fetcher.prefetch(result)
                    plan.fetcher = fetcher  # pinned: a hot swap may
                    #   re-derive bucket.fetcher (new output signature)
                    #   while this batch is in flight — collect must
                    #   fetch from the one the D2H was issued on
                    self.tracer.complete("serve_dispatch", t0, time.time(),
                                         TRACK_DISPATCH, seq=seq,
                                         frames=plan.valid,
                                         bucket=bucket.label())
                except Exception as e:  # noqa: BLE001 — drop this batch
                    sem.release()
                    self.router.discard(plan, kind=classify(e, "dispatch"))
                    if not self._contain(e, "dispatch", bucket=bucket):
                        return
                    continue
                # In-flight window: registered from now until the collect
                # side materializes (or discards) it; carries the plan so
                # a recovery can shed the sessions' claims even for a
                # batch a wedged collect thread is holding. The watchdog
                # (when armed) trips on this window's oldest age.
                self._window.add(seq, plan)
                bucket.adjust_inflight(1)
                q.put((seq, plan, result, t0))
                # Ledger stall accounting: this tick is the bucket's
                # dispatch heartbeat — it closes any reconfiguration
                # stall window open on the bucket (gap measured from
                # the last tick before the event to THIS one). One
                # attribute check when nothing is pending.
                bucket.last_dispatch_t = t0
                led = self.ledger
                if led is not None and led.has_pending_stalls:
                    led.note_dispatch(bucket.label(), t0)
                seq += 1
        except BaseException as e:  # noqa: BLE001
            self._fail(e)
        finally:
            self._dispatch_done.set()

    def _collect(self, gen: int = 0) -> None:
        chaos = self.config.chaos
        block_until_ready = None
        if self.attribution is not None:
            # Lineage needs the device/D2H split: block_until_ready
            # marks "device compute done, data still on device"; the
            # fetch that follows is then pure D2H+scatter. Without
            # lineage the fetch blocks on both at once (no extra sync).
            try:
                import jax

                block_until_ready = jax.block_until_ready
            except ImportError:  # pragma: no cover — jax is a hard dep
                pass
        q = self._inflight  # generation-pinned: recovery installs a fresh
        #   queue before starting the replacement thread, so a superseded
        #   thread can never pop (and then wrongly discard) a
        #   post-recovery batch — it only ever sees its own, drained,
        #   queue and whatever single item it was already holding.
        sem = self._inflight_sem  # pinned with the queue: a permit must be
        #   released into the semaphore it was acquired from — releasing
        #   the live attribute would over-credit a post-recovery window
        try:
            while self._collect_gen == gen:  # superseded by recovery → exit
                if chaos is not None:
                    chaos.fire("freeze")  # injection site: a delay rule
                    #   wedges this consumer (deterministic stall for the
                    #   watchdog tests)
                if self._supervisor is not None:
                    self._supervisor.beat("collect")
                try:
                    seq, plan, result, _t0 = q.get(timeout=0.05)
                except queue.Empty:
                    if self._dispatch_done.is_set() and q.empty():
                        break
                    continue
                bucket = plan.bucket
                fetcher = (plan.fetcher if plan.fetcher is not None
                           else (bucket.fetcher if bucket is not None
                                 else None))  # plan-pinned first: the
                #   bucket's fetcher may already belong to a hot-swapped
                #   successor program with a different output signature
                if plan.lin_marks is not None and block_until_ready is not None:
                    try:
                        block_until_ready(result)
                        plan.lin_marks.append(("device", time.time()))
                    except Exception:  # noqa: BLE001 — a poisoned batch
                        pass  # raises again in fetch below, where the
                        #   containment ladder owns it
                try:
                    # Streamed egress: shard host copies into the slot's
                    # preallocated slab (D2H issued at submit); fallback:
                    # the classic whole-batch np.asarray. Either way this
                    # waits for the device. The router copies rows out
                    # during route(), so handing it the pooled slab is
                    # safe — the slot only cycles max_inflight+1 batches
                    # later.
                    out = (fetcher.fetch(result, seq) if fetcher is not None
                           else np.asarray(result))
                    if plan.lin_marks is not None:
                        plan.lin_marks.append(("d2h", time.time()))
                    if chaos is not None:
                        # Chaos site "corrupt_device": one element of
                        # row 0 perturbed in an otherwise-valid batch —
                        # the silent corruption ONLY the shadow replay
                        # below can catch (it parses, routes, delivers).
                        out = maybe_corrupt_device(chaos, out)
                except Exception as e:  # noqa: BLE001 — poisoned batch
                    if self._collect_gen != gen:
                        # Superseded mid-wait: make sure the plan's
                        # session claims are released — discard is
                        # idempotent, so this is a no-op when recovery
                        # already shed it.
                        self.router.discard(plan)
                        continue
                    self._window.remove(seq)
                    sem.release()
                    if bucket is not None:
                        bucket.adjust_inflight(-1)
                    self.router.discard(plan, kind=classify(e, "collect"))
                    if not self._contain(e, "collect", bucket=bucket):
                        return
                    continue
                if self._collect_gen != gen:
                    # Recovery wrote this batch off while we materialized
                    # it: drop the result (semaphore replaced, no release)
                    # but release the session claims if the recovery could
                    # not see this plan (it was popped, so only the
                    # supervisor window — when armed — tracked it).
                    self.router.discard(plan)
                    continue
                self._window.remove(seq)
                sem.release()
                if bucket is not None:
                    # Live tick-cost sample for the EDF/cost bucket score
                    # (submit → materialized wall time, EWMA-smoothed;
                    # contended ticks are counted but not sampled — see
                    # the dispatch-side cost_sample comment).
                    bucket.observe_tick((time.time() - _t0) * 1e3,
                                        sample=plan.cost_sample,
                                        valid=plan.valid)
                    bucket.adjust_inflight(-1)
                self.tracer.complete("batch_complete", _t0, time.time(),
                                     TRACK_DEVICE, seq=seq,
                                     frames=plan.valid)
                if plan.audit_rows and self.audit is not None \
                        and bucket is not None:
                    # Pair each sampled input with its DELIVERED output
                    # (post any corrupt_device perturbation — the replay
                    # must judge what the client actually receives) and
                    # hand the pair to the off-thread golden worker.
                    for row, in_frame, sid, idx, lin in plan.audit_rows:
                        if row < plan.valid:
                            self.audit.submit_replay(
                                bucket.filter, in_frame,
                                np.array(out[row], copy=True),
                                session=sid, index=idx,
                                bucket=bucket.label(), lineage=lin,
                                out_uint8=bucket.engine.out_uint8)
                self.router.route(plan, out)
                if bucket is not None and bucket.draining_fetchers \
                        and bucket.inflight_batches == 0:
                    # The last pre-swap batch just routed (route copies
                    # rows out of the slab, so it is quiescent now):
                    # the old program's egress slabs can finally go.
                    bucket.release_drained_fetchers()
                # A materialized batch is proof of engine progress: the
                # consecutive-stall escalation counter starts over.
                self._stalls_since_progress = 0
        except BaseException as e:  # noqa: BLE001
            self._fail(e)

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        """Per-session stats plus the fleet aggregate p50/p99 export."""
        with self._lock:
            live = dict(self._sessions)
            retired = dict(self._retired)
            buckets = list(self._buckets)
        every = {**retired, **live}
        session_stats = {sid: s.stats() for sid, s in every.items()}
        return {
            "sessions": session_stats,
            "open_sessions": len(live),
            "retired_sessions": len(retired),
            # Standing work ahead of the device (queued frames) plus
            # batches in flight — the scrape endpoint's queue-depth
            # series and the fleet row's per-replica signal.
            "queue_depth": sum(len(s.ingress) + len(s.pending)
                               for s in live.values()),
            "inflight_batches": len(self._window),
            "draining": self._draining,
            "admission_rejections": self.admission_rejections,
            # Sum of the per-session counters (covers deadline sheds AND
            # hard-close discards) so the aggregate always reconciles
            # with the per-stream rows it sits beside; sessions evicted
            # from the retention bound leave the sum.
            "shed_total": sum(s["shed"] for s in session_stats.values()),
            "errors": self.errors,
            # Classified per-kind fault counters + last errors, budget
            # escalation levels, and supervised recoveries — the fleet
            # half of the fault model (per-tenant attribution is in each
            # session row's "faults").
            "faults": self.faults.summary(),
            "fault_budget": self._budget.summary(),
            "recoveries": self.recoveries,
            "continuity": self.continuity.summary(),
            # Hot-swap plane: committed stall-free substitutions (resize
            # / morph / recovery), contained aborts (old program kept
            # serving), and live chain morphs.
            "swaps": self.swaps,
            "swap_aborts": self.swap_aborts,
            "morphs": self.morphs,
            "engine_batches": sum(b.engine.stats.batches for b in buckets),
            "engine_frames": sum(b.engine.stats.frames for b in buckets),
            # Multi-signature plane: one row per live bucket (keyed by
            # canonical signature) + the compiled-program pool counters.
            "open_buckets": len(buckets),
            "buckets": {b.label(): b.stats_row() for b in buckets},
            "pool": self.pool.stats(),
            # Auto-plan plane: the Plan doc driving this frontend (None
            # = hand-set defaults) — provenance says cache/measured.
            **({"plan": self.applied_plan}
               if self.applied_plan is not None else {}),
            **self.router.stats(),
            "aggregate": LatencyStats.merged(
                [s.latency for s in every.values()]),
            **({"ingest": buckets[0].ingest_stats.summary()}
               if buckets[0].ingest_stats is not None else {}),
            **({"egress": buckets[0].egress_stats.summary()}
               if buckets[0].egress_stats is not None else {}),
            **({"supervisor": {
                    "stalls": self._supervisor.stalls,
                    "heartbeat_ages_s": self._supervisor.heartbeat_ages(),
                }} if self._supervisor is not None else {}),
            **({"chaos": self.config.chaos.summary()}
               if self.config.chaos is not None else {}),
            **({"trace": {"events": len(self.tracer),
                          "dropped_total": self.tracer.dropped}}
               if self.tracer.enabled else {}),
            **({"attribution": self.attribution.summary()}
               if self.attribution is not None else {}),
            **({"audit": self.audit.stats()}
               if self.audit is not None else {}),
            **({"ledger": self.ledger.summary(),
                "memory": self._memory_stats()}
               if self.ledger is not None else {}),
            **({"flight": self.flight.stats()}
               if self.flight is not None else {}),
            **({"broadcast": self.broadcast.stats()}
               if self.broadcast is not None else {}),
            **({"control": {
                    **self.control_plane.stats(),
                    "quality_rebinds": self.quality_rebinds,
                    "quality_rebinds_dropped": self.quality_rebinds_dropped,
                    "resize_compile_errors": self.resize_compile_errors,
                    "admission_tier_floor": self._admission_tier_floor,
                }} if self.control_plane is not None else {}),
        }


class ZmqStreamBridge:
    """One reference-style client ↔ one frontend session, over the wire
    framing of ``transport.zmq_ingress`` (READY credits on a DEALER, raw
    results on a PUSH — behaviorally a very fast single worker).

    The remote app keeps its own frame index space; each frame's remote
    index rides through the session as the slot ``tag`` and is echoed
    back in the result message, so the app's reorder buffer works
    unmodified while the session uses its private index space internally.
    """

    def __init__(
        self,
        frontend: ServeFrontend,
        host: str = "localhost",
        distribute_port: int = 5555,
        collect_port: int = 5556,
        use_jpeg: bool = True,
        raw_size: int = 512,
        jpeg_quality: int = 90,
        codec_threads: int = 4,
        encode_depth: int = 2,
        poll_ms: int = 10,
        slo_ms: Optional[float] = None,
        wire: Optional[str] = None,
        delta_tile: int = 32,
        delta_keyframe_interval: int = 16,
        delta_threshold: int = 0,
        delta_degrade_after: int = 8,
        audit_wire: bool = False,
        heartbeat: Optional[HeartbeatConfig] = None,
    ):
        import zmq

        from dvf_tpu.transport.codec import WIRE_MODES, make_wire_codec
        from dvf_tpu.transport.zmq_ingress import READY

        if wire is None:
            wire = "jpeg" if use_jpeg else "raw"
        if wire not in WIRE_MODES:
            raise ValueError(f"wire must be one of {WIRE_MODES}, "
                             f"got {wire!r}")
        self._zmq = zmq
        self._ready = READY
        self.frontend = frontend
        self.session_id = frontend.open_stream(slo_ms=slo_ms)
        self.wire = wire
        if wire == "delta":
            # Temporal-delta wire, both directions of this bridge: one
            # DeltaCodec instance carries independent encoder (result
            # deliveries — a single SESSION's frames, so they are
            # sequential even though the engine batch under them is
            # cross-tenant) and decoder (incoming app frames) state.
            self.codec = make_wire_codec(
                "delta", quality=jpeg_quality, threads=codec_threads,
                tile=delta_tile,
                keyframe_interval=delta_keyframe_interval,
                delta_threshold=delta_threshold,
                on_gap="raise")
        else:
            self.codec = make_wire_codec("jpeg", quality=jpeg_quality,
                                         threads=codec_threads)
        # Bounded delta degradation (the bridge has no fault-budget
        # ladder of its own): this many contained wire errors flip the
        # encoder to full-frame keyframes — the peer decodes those
        # unchanged, at full-frame JPEG cost.
        self._delta_degrade_after = delta_degrade_after
        self._delta_errors = 0
        self.wire_degraded = False
        # Asynchronous codec plane (runtime/egress.py): deliveries polled
        # from the session are batch-encoded on the codec pool while the
        # loop keeps pumping credits/frames; completed batches drain in
        # order. Raw mode rides the same plane as zero-copy memoryviews.
        self.plane = AsyncCodecPlane(self.codec, jpeg=(wire != "raw"),
                                     depth=encode_depth)
        # Lineage extension past delivery (lineage-armed frontends): the
        # bridge marks encode/send on each delivery's FrameLineage and
        # folds the wire components back into the frontend's attribution
        # plane — "21% encode" in explain() comes from here.
        self._attr = frontend.attribution
        # Wire-integrity audit (obs.audit): incoming frames must pass
        # the digest envelope, outgoing deliveries are stamped
        # post-encode; counters fold into the frontend's audit plane
        # when one is armed. Strict ingress — audit-mode peers stamp.
        self._wire_in = None
        self._wire_out = None
        if audit_wire:
            from dvf_tpu.obs.audit import WireAudit

            self._wire_in = WireAudit("bridge_ingress")
            self._wire_out = WireAudit("bridge_egress",
                                       chaos=frontend.config.chaos)
            if frontend.audit is not None:
                frontend.audit.register_wire(self._wire_in)
                frontend.audit.register_wire(self._wire_out)
        self.use_jpeg = wire != "raw"
        self.raw_size = raw_size
        self.poll_ms = poll_ms
        self.errors = 0
        # Continuity plane (resilience.continuity): when a
        # HeartbeatConfig is armed, silence on the DEALER beyond
        # timeout_s is declared a PARTITION — counted, classified into
        # the frontend's fault stats, ledgered, and answered with a
        # jittered-backoff socket reconnect instead of pumping credits
        # into a dead wire forever. None = legacy behavior (off).
        self.heartbeat = heartbeat.validate() if heartbeat else None
        self.continuity = ContinuityStats()
        self._reconnect = (ReconnectPolicy(self.heartbeat)
                           if self.heartbeat else None)
        self.send_retries = 0  # zmq.Again re-sends of an already-encoded
        #   delivery (the PR 5 single-encode cache makes these free of
        #   re-encode cost; the counter proves the retry path is taken)
        self._dealer_endpoint = f"tcp://{host}:{distribute_port}"
        self.ctx = zmq.Context()
        self.dealer = self.ctx.socket(zmq.DEALER)
        self.dealer.connect(self._dealer_endpoint)
        self.push = self.ctx.socket(zmq.PUSH)
        self.push.setsockopt(zmq.SNDTIMEO, 1000)
        self.push.connect(f"tcp://{host}:{collect_port}")
        self._stop = threading.Event()

    def _repartition_dealer(self) -> float:
        """Declare the ingress link partitioned: count + classify +
        ledger the event, rebuild the DEALER socket (drops the stale
        identity and any queued credits), and return the jittered
        backoff delay the caller should wait before resuming the pump."""
        self.continuity.inc("partitions")
        err = TimeoutError(
            f"no traffic on {self._dealer_endpoint} for "
            f"{self.heartbeat.timeout_s:.1f}s")
        self.frontend.faults.record(FaultKind.PARTITION, err)
        if self.frontend.ledger is not None:
            self.frontend.ledger.record(
                ledger_mod.PARTITION, cause=ledger_mod.CAUSE_RECOVERY,
                peer=self._dealer_endpoint, plane="bridge",
                attempt=self._reconnect.attempt)
        self.dealer.close(0)
        self.dealer = self.ctx.socket(self._zmq.DEALER)
        self.dealer.connect(self._dealer_endpoint)
        return self._reconnect.next_delay()

    def stop(self) -> None:
        self._stop.set()

    def stats(self) -> dict:
        return {
            "errors": self.errors,
            "send_retries": self.send_retries,
            "wire_degraded": self.wire_degraded,
            "continuity": self.continuity.summary(),
        }

    def _delta_fault(self) -> None:
        """Count one contained delta-wire fault; past the bound, degrade
        the encoder to full-frame keyframes (stays decodable by the same
        peer — the wire is framed either way)."""
        if self.wire != "delta" or self.wire_degraded:
            return
        self._delta_errors += 1
        if self._delta_errors >= self._delta_degrade_after:
            self.codec.full_frames = True
            self.wire_degraded = True
            print("[ZmqStreamBridge] repeated delta wire faults: "
                  "degrading to full-frame JPEG (keyframe-only)",
                  file=sys.stderr, flush=True)

    def _decode(self, payload: bytes) -> np.ndarray:
        if self.use_jpeg:
            h, w = self.codec.probe(payload)
            out = np.empty((h, w, 3), np.uint8)
            self.codec.decode_batch([payload], out=out[None])
            return out
        return np.frombuffer(payload, np.uint8).reshape(
            self.raw_size, self.raw_size, 3)

    def run(self, max_frames: Optional[int] = None) -> None:
        """Credit-pump loop: READY credits out, frames in, deliveries
        back. Same per-iteration containment as TpuZmqWorker.run."""
        import collections
        import os

        from dvf_tpu.transport.zmq_ingress import parse_frame_reply, result_msg

        pid = str(os.getpid()).encode()
        credits = 0
        served = 0
        budget = self.frontend.config.queue_size
        last_rx = time.monotonic()  # liveness clock: any DEALER traffic
        partitioned = False         # a reconnect is pending confirmation
        # Encoded deliveries not yet on the wire: a send timeout (stalled
        # PULL peer) must re-try them next iteration, not discard frames
        # that survived every other drop-bound in the system. Entries are
        # (delivery, payload) — encoding happened on the codec plane, so
        # a retry never pays the encode twice.
        out_pending: "collections.deque" = collections.deque()
        while not self._stop.is_set():
            in_send = False  # containment scope: True only while the
            #   head out_pending delivery is being sent
            try:
                while credits < budget:
                    try:
                        self.dealer.send(self._ready, flags=self._zmq.NOBLOCK)
                    except self._zmq.Again:
                        break
                    credits += 1
                if self.dealer.poll(self.poll_ms):
                    parts = self.dealer.recv_multipart()
                    credits = max(0, credits - 1)
                    last_rx = time.monotonic()
                    if partitioned:
                        # Traffic after a partition = the reconnect took:
                        # count it and reset the backoff ladder.
                        partitioned = False
                        self._reconnect.reset()
                        self.continuity.inc("reconnects")
                    parsed = parse_frame_reply(parts)
                    if parsed is None:
                        self.errors += 1
                    else:
                        remote_idx, payload = parsed
                        if self._wire_in is not None:
                            # Verify + strip the audit envelope before
                            # decode: a flipped bit on the wire raises
                            # WireIntegrityError into this loop's
                            # containment (counted, frame dropped)
                            # instead of decoding corrupt pixels.
                            payload = self._wire_in.verify(payload)
                        self.frontend.submit(
                            self.session_id, self._decode(payload),
                            tag=(remote_idx, time.time()))
                else:
                    credits = max(0, credits - 1)  # credit decay, see
                    #   transport.zmq_ingress._run_loop
                    if (self.heartbeat is not None
                            and (time.monotonic() - last_rx)
                            > self.heartbeat.timeout_s):
                        delay = self._repartition_dealer()
                        partitioned = True
                        credits = 0  # the old socket's credits died with it
                        last_rx = time.monotonic() + delay  # next liveness
                        #   window opens after the backoff — a dead peer
                        #   repartitions once per (timeout + backoff), so
                        #   the backoff ladder, not the timeout, paces it
                        self._stop.wait(delay)
                # All pending deliveries go to the codec plane as ONE
                # batch encode (pool-parallel), overlapped with the next
                # iteration's decode/submit work; raw frames ride as
                # zero-copy memoryviews (zmq copies at send).
                fresh = self.frontend.poll(self.session_id)
                if fresh:
                    self.plane.submit([d.frame for d in fresh], fresh)
                for batch in self.plane.ready(
                        block=len(self.plane) > self.plane.depth):
                    enc_t = time.time()
                    for d, payload, err in batch:
                        if err is not None:
                            self.errors += 1  # one bad frame: dropped
                            self._delta_fault()
                            print(f"[ZmqStreamBridge] encode failed "
                                  f"(dropping frame): {err!r}",
                                  file=sys.stderr)
                            continue
                        if self._attr is not None \
                                and d.lineage is not None:
                            d.lineage.mark("encode", enc_t)
                        if self._wire_out is not None:
                            # Stamp ONCE per frame, at enqueue: a
                            # zmq.Again retry must re-send the same
                            # stamped bytes, not re-stamp (which would
                            # inflate the stamp counter and advance the
                            # corrupt_wire chaos event index per
                            # ATTEMPT instead of per frame).
                            payload = self._wire_out.stamp(payload)
                        out_pending.append((d, payload))
                while out_pending:
                    d, payload = out_pending[0]
                    in_send = True  # head delivery is now the one at risk
                    remote_idx, t0 = d.tag
                    try:
                        self.push.send_multipart(result_msg(
                            remote_idx, pid, t0, time.time(), payload))
                    except self._zmq.Again:
                        self.send_retries += 1  # same encoded payload is
                        #   re-sent next iteration — never re-encoded
                        break  # peer stalled: keep the tail, retry later
                    out_pending.popleft()
                    if self._attr is not None and d.lineage is not None:
                        d.lineage.mark("send")
                        self._attr.observe_wire(d.lineage)
                    served += 1
                    in_send = False
                if max_frames is not None and served >= max_frames:
                    break
            except Exception as e:  # noqa: BLE001 — per-iteration containment
                self.errors += 1
                from dvf_tpu.transport.codec import DeltaWireError

                if isinstance(e, DeltaWireError):
                    self._delta_fault()
                if in_send and out_pending:
                    # The head delivery's OWN send raised (never zmq.Again
                    # — that breaks out above): drop that one frame so
                    # containment cannot spin on it forever. Errors from
                    # the ingest half of the iteration leave out_pending
                    # untouched — a queued good frame must not pay for a
                    # corrupt incoming payload.
                    out_pending.popleft()
                print(f"[ZmqStreamBridge] error (continuing): {e!r}",
                      file=sys.stderr)
        # Loop exit (stop() / max_frames): flush the codec plane and
        # attempt the tail sends — frames already consumed from the
        # session must not vanish because they were mid-encode when the
        # loop ended (the worker's exit drain, mirrored; codec.close in
        # close() would otherwise cancel the pending futures). Best
        # effort: a stalled peer's zmq.Again bounds each send at SNDTIMEO.
        try:
            for batch in self.plane.flush():
                for d, payload, err in batch:
                    if err is None:
                        if self._wire_out is not None:
                            payload = self._wire_out.stamp(payload)
                        out_pending.append((d, payload))
                    else:
                        self.errors += 1
            while out_pending:
                d, payload = out_pending.popleft()
                remote_idx, t0 = d.tag
                self.push.send_multipart(result_msg(
                    remote_idx, pid, t0, time.time(), payload))
                served += 1
        except Exception as e:  # noqa: BLE001 — teardown best-effort
            self.errors += 1
            print(f"[ZmqStreamBridge] exit drain failed (dropping tail): "
                  f"{e!r}", file=sys.stderr)

    def close(self) -> None:
        self._stop.set()
        try:
            self.frontend.close(self.session_id, drain=False)
        except KeyError:
            pass
        self.codec.close()
        self.dealer.close(0)
        self.push.close(0)
        self.ctx.term()
