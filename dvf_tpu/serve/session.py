"""Per-stream sessions for the multi-tenant serving frontend.

A ``StreamSession`` is one client stream's slice of the shared frontend:
its own frame index space, its own drop-oldest ingress queue (the same
``sched.queues.DropOldestQueue`` the single-stream pipeline uses — the
reference's distributor.py:188-203 backpressure, now per tenant), its own
sink-side reorder cursor, and its own latency SLO budget. Nothing here
touches the device — sessions are pure host bookkeeping that the
continuous batcher (serve.batcher) and result router (serve.router)
operate over.

Frame lifecycle through a session:

  submit → ingress (drop-oldest bound) → pending (scheduler-owned, EDF
  order) → device slot tagged (session_id, frame_index) → reorder buffer
  → out queue / sink

Freshness is enforced twice: at the ingress bound (drop-oldest, exactly
like the single-stream pipeline) and at the SLO deadline (a frame whose
latency budget has expired before it reaches a device slot is shed by the
batcher — processing it would spend device time on a result the client
has already given up on).
"""

from __future__ import annotations

import collections
import dataclasses
import sys
import threading
import time
from typing import Any, NamedTuple, Optional

import numpy as np

from dvf_tpu.obs.lineage import FrameLineage
from dvf_tpu.obs.metrics import LatencyStats
from dvf_tpu.resilience.continuity import ReplayRing
from dvf_tpu.sched.queues import DropOldestQueue
from dvf_tpu.sched.reorder import ReorderBuffer

# Session lifecycle: OPEN accepts submits; CLOSING serves what's queued /
# in flight but rejects new frames; CLOSED is fully retired (tail
# delivered, sink closed) and only poll() still works.
OPEN, CLOSING, CLOSED = "open", "closing", "closed"


class ServeError(RuntimeError):
    """Base class for serving-frontend errors."""


class AdmissionError(ServeError):
    """The frontend refused to admit a new session (max_sessions)."""


class SessionClosedError(ServeError):
    """submit() on a session that is closing or closed."""


@dataclasses.dataclass
class SessionConfig:
    queue_size: int = 10          # ingress bound, drop-oldest beyond
    slo_ms: float = 1000.0        # per-frame latency budget (submit → deliver)
    frame_delay: int = 0          # reorder cursor lag; 0 = deliver ASAP
    reorder_capacity: int = 50
    out_queue_size: int = 64      # poll()-side bound, drop-oldest beyond
    tier: int = 1                 # priority tier (control.controllers:
    #   0 interactive, 1 standard, 2 batch — lower sheds LAST): breaks
    #   EDF ties in the batcher's slot pick, orders the quality
    #   controller's downshift victims, and is what the admission floor
    #   refuses by under sustained overload
    replay_window: int = 64       # delivered-tail frames retained for
    #   the continuity plane's resume replay (resilience.continuity):
    #   a reconnecting client replays from its last-seen index and
    #   dedups, upgrading delivery to effectively-exactly-once within
    #   this window. 0 disables the ring (no frame references pinned).


@dataclasses.dataclass
class Slot:
    """One frame's claim on a device batch slot: the (session, index) tag
    that demultiplexes the shared batch back to its stream."""

    session: "StreamSession"
    index: int
    ts: float           # capture/submit timestamp (latency clock)
    deadline: float     # ts + slo; the batcher sheds past-deadline slots
    frame: Optional[np.ndarray]  # cleared once staged into the batch
    tag: Any = None     # opaque client cookie (e.g. the ZMQ bridge's
    #   remote frame index), threaded through to the Delivery
    lin: Any = None     # obs.lineage.FrameLineage when the frontend's
    #   attribution plane is armed: the frame's hop trail, marked at
    #   each queue/stage boundary and closed at delivery — None (zero
    #   cost) otherwise


class Delivery(NamedTuple):
    """One processed frame handed back to the client."""

    index: int
    frame: np.ndarray
    capture_ts: float
    latency_ms: float
    tag: Any
    lineage: Any = None  # FrameLineage (lineage-armed frontends): the
    #   additive latency decomposition behind latency_ms; rides the
    #   ProcessReplica RPC so the fleet front door can re-base and
    #   extend it


class StreamSession:
    """One tenant stream multiplexed onto the shared engine.

    Thread contract: ``submit``/``poll``/``close`` may be called from any
    client thread; ``drain_ingress``/``shed_expired``/``pending`` are
    owned by the frontend's dispatch thread; delivery methods are owned
    by the frontend's collect thread. Cross-thread state (lifecycle,
    counters) is lock-protected.
    """

    def __init__(
        self,
        session_id: str,
        config: Optional[SessionConfig] = None,
        sink: Any = None,
    ):
        self.id = session_id
        self.config = config or SessionConfig()
        self.sink = sink
        self.attribution: Any = None  # obs.lineage.AttributionPlane when
        #   the owning frontend armed frame-lineage attribution (set at
        #   registration): submit then opens a FrameLineage per frame
        #   and deliver_ready closes + folds it. None = lineage off,
        #   zero per-frame cost.
        self.bucket: Any = None  # the signature bucket this session is
        #   bound to (serve.server._Bucket, set at admission): which
        #   compiled program serves it, which geometry its frames must
        #   match, and where its faults/budget overflow attribute
        # -- load-adaptive quality state (dvf_tpu.control) --------------
        self.quality_level = 0   # 0 = full quality; level L frames are
        #   decimated ×2^L per axis at submit and served by a bucket
        #   whose op chain ends in upscale(scale=2^L), so DELIVERIES are
        #   always full resolution (bit-exactness waived while > 0)
        self.base_sig: Any = None    # (frame_shape, np_dtype) of the
        #   full-quality signature, captured at the first downshift so
        #   recovery can route home even if the base bucket retired
        self.base_chain: Any = None  # the full-quality canonical chain
        self.quality_shifts = 0      # lifetime level changes (stats)
        self.ingress = DropOldestQueue(maxsize=self.config.queue_size)
        # Scheduler-owned staging between ingress and the device: the
        # EDF/shed scan needs to see every queued frame, which the
        # drop-oldest queue doesn't expose. Only the dispatch thread
        # touches it.
        self.pending: "collections.deque[Slot]" = collections.deque()
        self.reorder = ReorderBuffer(
            frame_delay=self.config.frame_delay,
            capacity=self.config.reorder_capacity,
        )
        # poll() path when no sink is attached. DropOldestQueue again: a
        # client that stops polling bounds memory and keeps freshness.
        self.out = DropOldestQueue(maxsize=self.config.out_queue_size)
        # Delivered-tail replay ring (resilience.continuity): every
        # delivered frame is ALSO recorded here (by index) so a resumed
        # client can replay the tail it may have missed across a
        # disconnect. References only — the ring pins at most
        # replay_window frames beyond what the out queue already holds.
        self.replay = (ReplayRing(self.config.replay_window)
                       if self.config.replay_window > 0 else None)
        self.latency = LatencyStats()
        self._lock = threading.Lock()
        # Serializes delivery (advance → pop_ready → emit): finalize
        # (dispatch thread) and route (collect thread) may both call
        # deliver_ready on a closing session; unserialized, the later
        # indices could reach the out queue before the earlier ones.
        self._deliver_lock = threading.Lock()
        self.state = OPEN
        self._discard = False   # close(drain=False): shed queued frames
        self.next_index = 0     # this stream's private frame index space
        self.inflight = 0       # slots currently inside a device batch
        self.submitted = 0
        self.delivered = 0
        self.shed = 0           # frames dropped for a blown SLO deadline
        self.slo_miss = 0       # delivered, but past the SLO budget
        self.failed = 0         # frames lost to a failed device batch
        self.faults: dict = {}  # the same losses, classified by FaultKind
        #   (resilience.faults) — per-tenant fault attribution, poll-able
        #   through stats() beside the aggregate counters
        self.sink_errors = 0    # contained per-frame sink failures
        self.tap = None         # broadcast publish hook (set by the
        #   frontend when this session publishes a channel): called per
        #   delivered frame AFTER the session's own sink/out delivery —
        #   the publisher's interactive path is never behind fan-out,
        #   and the tap itself only does one frame copy + one bounded
        #   enqueue (broadcast.channel.Channel.offer)
        self.tap_errors = 0     # contained tap failures (same policy
        #   as sink_errors: drop the fan-out frame, keep serving)
        self._last_deadline = float("-inf")

    # -- client side (any thread) --------------------------------------

    def submit(self, frame: np.ndarray, ts: Optional[float] = None,
               tag: Any = None) -> int:
        """Enqueue one frame; returns its index in this stream's space.

        Never blocks: a full ingress queue evicts the oldest frame
        (drop-oldest, distributor.py:193-203 semantics). The frame array
        is referenced, not copied, until the batcher stages it — callers
        that reuse their capture buffer must pass a copy.
        """
        ts = time.time() if ts is None else ts
        lin = None
        if self.attribution is not None:
            # The lineage clock starts at the CLIENT's capture ts, so
            # the decomposition telescopes to exactly the latency_ms the
            # delivery reports (capture→deliver).
            lin = FrameLineage(self.id, -1, ts)
        # ONE atomic section for state check, index, deadline clamp, AND
        # the enqueue: concurrent submits that clamped in one order but
        # enqueued in the other would put a later deadline ahead of an
        # earlier one, breaking the EDF prefix invariant the batcher's
        # popleft relies on; and a put outside the state check could land
        # in the ingress of a session close() just finalized, stranding
        # the frame forever.
        with self._lock:
            if self.state != OPEN:
                raise SessionClosedError(
                    f"session {self.id!r} is {self.state}")
            idx = self.next_index
            self.next_index += 1
            self.submitted += 1
            # Deadlines must be monotonic within a stream — clients pass
            # arbitrary capture timestamps (jitter, clock steps), so
            # clamp rather than trust.
            deadline = max(self._last_deadline, ts + self.config.slo_ms / 1e3)
            self._last_deadline = deadline
            if lin is not None:
                lin.frame_index = idx
            self.ingress.put(Slot(
                session=self, index=idx, ts=ts,
                deadline=deadline, frame=frame, tag=tag, lin=lin))
        return idx

    def poll(self, max_items: Optional[int] = None) -> list:
        """Pop up to ``max_items`` completed ``Delivery`` records (all
        ready ones when None). Empty list = nothing ready. Valid on
        closed sessions until the tail is drained."""
        if self.sink is not None:
            raise ServeError(
                f"session {self.id!r} delivers through its sink; poll() "
                f"only applies to sink-less sessions")
        n = max_items if max_items is not None else len(self.out)
        return self.out.pop_up_to(n)

    # -- scheduler side (dispatch thread only) -------------------------

    def drain_ingress(self) -> None:
        """Move every queued frame from the ingress bound into the
        scheduler's pending staging (or shed everything queued, if the
        session was closed with ``drain=False``)."""
        if self._discard:
            n = len(self.pending) + len(
                self.ingress.pop_up_to(len(self.ingress)))
            self.pending.clear()
            if n:
                with self._lock:
                    self.shed += n
            return
        got = self.ingress.pop_up_to(len(self.ingress))
        if got and self.attribution is not None:
            # One stamp per drain, shared across the drained slots: the
            # end of each frame's session-ingress-queue component.
            now = time.time()
            for slot in got:
                if slot.lin is not None:
                    slot.lin.mark("queue_ingress", now)
        self.pending.extend(got)

    def flush_queued(self, count_shed: bool = True) -> int:
        """Drop everything queued (pending + ingress) — the
        quality-rebind flush: frames queued at the OLD geometry cannot
        be staged into the new bucket's program. Dispatch-thread only
        (owns ``pending``). ``count_shed=False`` keeps the loss out of
        ``shed`` — the control plane's pressure predicate watches
        ``shed_total``, and a flush caused by the controller's OWN
        quality move must not read back as fresh overload evidence (the
        frontend counts these separately)."""
        n = len(self.pending) + len(
            self.ingress.pop_up_to(len(self.ingress)))
        self.pending.clear()
        if n and count_shed:
            with self._lock:
                self.shed += n
        return n

    def shed_expired(self, now: float) -> int:
        """Drop pending frames whose SLO deadline has passed. Deadlines
        are monotonic within a stream (fixed slo, monotonic submit ts),
        so expired frames are always a prefix."""
        n = 0
        while self.pending and self.pending[0].deadline < now:
            self.pending.popleft()
            n += 1
        if n:
            with self._lock:
                self.shed += n
        return n

    # -- delivery side (collect thread only) ---------------------------

    def claim_inflight(self, n: int) -> None:
        """The batcher moved n of this stream's frames into a device
        batch (dispatch thread)."""
        with self._lock:
            self.inflight += n

    def complete(self, slot: Slot, frame: np.ndarray) -> None:
        """One processed frame arrived from the device.

        The reorder insert and the in-flight decrement are one atomic
        step w.r.t. ``drained()``: decrementing first and inserting
        after the lock would let the dispatch thread observe
        inflight == 0, finalize, and flush the reorder buffer *between*
        the two — permanently losing the final frame of a gracefully
        closing session.
        """
        with self._lock:
            self.inflight -= 1
            if self.state != CLOSED:  # late result after hard close: dropped
                self.reorder.complete(
                    slot.index, (frame, slot.ts, slot.tag, slot.lin))

    def discard_inflight(self, n: int = 1, kind: str = None) -> None:
        """A device batch failed; its slots never produced results.
        Counted (``failed``, and per fault ``kind`` when one is given —
        shutdown discards pass None) so the per-session accounting
        identity submitted == delivered + shed + failed +
        dropped_at_ingress still reconciles after contained errors."""
        with self._lock:
            self.inflight -= n
            self.failed += n
            if kind is not None:
                self.faults[kind] = self.faults.get(kind, 0) + n

    def deliver_ready(self) -> int:
        """Advance the reorder cursor and emit everything ready; returns
        the number of frames delivered. Serialized by _deliver_lock so
        concurrent callers (collect thread vs finalize) cannot interleave
        out of index order."""
        n = 0
        closed = None
        with self._deliver_lock:
            self.reorder.advance()
            for idx, (frame, ts, tag, lin) in self.reorder.pop_ready():
                now = time.time()
                lat_s = now - ts
                self.latency.record(lat_s)
                with self._lock:
                    self.delivered += 1
                    if lat_s * 1e3 > self.config.slo_ms:
                        self.slo_miss += 1
                if lin is not None and self.attribution is not None:
                    # Close the lineage on the SAME clock read latency
                    # is computed from, so the additive decomposition
                    # sums to latency_ms exactly (the invariant the
                    # golden tests pin); the fold happens once per
                    # delivery round below, not per frame.
                    lin.mark("deliver", now)
                    if closed is None:
                        closed = []
                    closed.append((lin, lat_s * 1e3))
                d = Delivery(idx, frame, ts, lat_s * 1e3, tag, lin)
                if self.replay is not None:
                    # Record BEFORE the sink/out handoff: a frame the
                    # client's side of the wire lost is still resumable.
                    self.replay.push(idx, d)
                if self.sink is not None:
                    try:
                        self.sink.emit(idx, frame, ts)
                    except Exception as e:  # noqa: BLE001 — one tenant's
                        # sink hiccup must never kill the shared frontend
                        # (Pipeline._contain's 'sink' semantics, per
                        # session): drop the frame, count, keep serving.
                        with self._lock:
                            self.sink_errors += 1
                        print(f"[serve:sink:{self.id}] error (continuing): "
                              f"{e!r}", file=sys.stderr, flush=True)
                else:
                    self.out.put(d)
                if self.tap is not None:
                    try:
                        self.tap(idx, frame, ts)
                    except Exception as e:  # noqa: BLE001 — broadcast
                        # fan-out trouble must never kill the
                        # publisher's own delivery (sink containment
                        # policy, applied to the tap)
                        with self._lock:
                            self.tap_errors += 1
                        print(f"[serve:tap:{self.id}] error (continuing): "
                              f"{e!r}", file=sys.stderr, flush=True)
                n += 1
            if closed is not None:
                bucket = self.bucket
                self.attribution.observe_batch(
                    closed, self.config.slo_ms,
                    bucket.label() if bucket is not None else None)
        return n

    # -- lifecycle ------------------------------------------------------

    def close(self, drain: bool = True) -> None:
        """Stop accepting frames. ``drain=True`` lets queued and
        in-flight frames flow through (the frontend finalizes the session
        once they have); ``drain=False`` discards the queue too."""
        with self._lock:
            if self.state != OPEN:
                return
            self.state = CLOSING
            # pending/ingress are dispatch-thread-owned; flag them for
            # shedding there (drain_ingress) rather than racing the
            # batcher from a client thread.
            self._discard = not drain

    def drained(self) -> bool:
        """True when nothing of this stream remains queued or in flight
        (the frontend's finalize condition for a closing session)."""
        with self._lock:
            return (self.state == CLOSING and self.inflight == 0
                    and not self.pending and len(self.ingress) == 0)

    def finalize(self) -> None:
        """Deliver the reorder tail, close the sink, mark CLOSED.
        Called by the frontend once ``drained()`` (or at shutdown, where
        frames may still be queued — they are counted as shed here so
        the accounting identity survives an early stop())."""
        with self._lock:
            if self.state == CLOSED:
                return
            leftover = len(self.pending) + len(
                self.ingress.pop_up_to(len(self.ingress)))
            self.pending.clear()
            self.shed += leftover  # no-op on the drained() path
        self.reorder.flush()
        self.deliver_ready()
        with self._lock:
            self.state = CLOSED
        if self.sink is not None and hasattr(self.sink, "close"):
            self.sink.close()

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "submitted": self.submitted,
                "delivered": self.delivered,
                "shed": self.shed,
                "slo_miss": self.slo_miss,
                "failed": self.failed,
                "faults": dict(self.faults),
                "sink_errors": self.sink_errors,
                "tap_errors": self.tap_errors,
                "dropped_at_ingress": self.ingress.dropped,
                "dropped_unpolled": self.out.dropped,  # delivered but
                #   evicted from the poll queue before the client read it
                "inflight": self.inflight,
                "slo_ms": self.config.slo_ms,
                "tier": self.config.tier,
                "quality_level": self.quality_level,
                "quality_shifts": self.quality_shifts,
                **self.latency.summary(),
            }

    def __repr__(self) -> str:  # debugging aid
        return (f"StreamSession({self.id!r}, {self.state}, "
                f"submitted={self.submitted}, delivered={self.delivered})")
