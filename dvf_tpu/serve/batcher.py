"""Continuous cross-session batcher.

The single-stream pipeline fills each device batch from ONE queue
(`runtime.pipeline._assemble`); when that stream is slow, the batch pads
and TPU utilization collapses. This batcher generalizes the assembler
across tenants: every tick it drains ready frames from *all* sessions and
packs them into one fixed-signature device batch — slots tagged
``(session_id, frame_index)``, short batches padded with a repeat of the
last valid row exactly like the single-stream assembler (static shapes →
one compilation; the ``valid`` count drops padded outputs on the way
back).

Scheduling policy (the genuinely new multi-tenant part):

- **EDF across sessions.** Candidate slots are ordered by SLO deadline
  (submit ts + the session's latency budget) and the earliest deadlines
  win the batch. With equal SLOs this degrades to global FIFO by arrival
  — fair by construction; a tighter-SLO stream gets priority exactly
  proportional to how much less slack it has. Deadlines are monotonic
  within a stream, so EDF always picks a per-session *prefix* and
  per-session ordering is preserved end to end.
- **Shed by SLO headroom when oversubscribed.** Losing slots stay queued
  and age; once a frame's deadline passes before it reaches a device
  slot it is shed (counted per session) rather than processed — device
  time is never spent on a result the client's latency budget has
  already written off. Undersubscribed systems never shed: every frame
  makes the next batch.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from dvf_tpu.serve.session import Slot, StreamSession


@dataclasses.dataclass
class BatchPlan:
    """One tick's device batch: the staged array, how many rows are real,
    and the (session, frame_index) tag per valid row."""

    batch: np.ndarray
    valid: int
    slots: List[Slot]


class ContinuousBatcher:
    """Drains ready frames across sessions into fixed-signature batches."""

    def __init__(self, batch_size: int):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = batch_size

    def plan(
        self,
        sessions: Sequence[StreamSession],
        now: float,
        staging: Optional[np.ndarray] = None,
    ) -> Optional[BatchPlan]:
        """Assemble one batch from everything ready; None = nothing to do.

        ``staging``: preallocated (batch_size, H, W, C) buffer to fill
        (the frontend's per-inflight-slot pool); a fresh array is
        allocated when omitted (tests).

        Dispatch-thread only: touches the sessions' scheduler-owned
        ``pending`` staging.
        """
        candidates: List[Slot] = []
        for s in sessions:
            s.drain_ingress()
            s.shed_expired(now)  # counted on the session (stats() sums)
            candidates.extend(s.pending)
        if not candidates:
            return None
        # EDF: earliest SLO deadline first. Stable sort + per-session
        # monotonic deadlines (a hard guarantee — submit clamps each
        # deadline to at least the previous one, whatever client ts
        # says) ⇒ the chosen set is a prefix of each session's pending
        # deque, so popleft below removes exactly the chosen slots.
        candidates.sort(key=lambda slot: slot.deadline)
        chosen = candidates[: self.batch_size]
        taken_per_session: dict = {}
        for slot in chosen:
            taken_per_session[slot.session] = (
                taken_per_session.get(slot.session, 0) + 1)
        for s, n in taken_per_session.items():
            for _ in range(n):
                s.pending.popleft()
            s.claim_inflight(n)

        valid = len(chosen)
        if staging is None:
            f0 = chosen[0].frame
            staging = np.empty((self.batch_size, *f0.shape), dtype=f0.dtype)
        for row, slot in enumerate(chosen):
            np.copyto(staging[row], slot.frame)
            slot.frame = None  # drop the client's buffer reference
        for row in range(valid, self.batch_size):
            np.copyto(staging[row], staging[valid - 1])
        return BatchPlan(batch=staging, valid=valid, slots=chosen)
