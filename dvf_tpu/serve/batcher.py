"""Continuous cross-session batcher.

The single-stream pipeline fills each device batch from ONE queue
(`runtime.pipeline._assemble`); when that stream is slow, the batch pads
and TPU utilization collapses. This batcher generalizes the assembler
across tenants: every tick it drains ready frames from *all* sessions and
packs them into one fixed-signature device batch — slots tagged
``(session_id, frame_index)``, short batches padded with a repeat of the
last valid row exactly like the single-stream assembler (static shapes →
one compilation; the ``valid`` count drops padded outputs on the way
back).

Scheduling policy (the genuinely new multi-tenant part):

- **EDF across sessions.** Candidate slots are ordered by SLO deadline
  (submit ts + the session's latency budget) and the earliest deadlines
  win the batch. With equal SLOs this degrades to global FIFO by arrival
  — fair by construction; a tighter-SLO stream gets priority exactly
  proportional to how much less slack it has. Deadlines are monotonic
  within a stream, so EDF always picks a per-session *prefix* and
  per-session ordering is preserved end to end.
- **Shed by SLO headroom when oversubscribed.** Losing slots stay queued
  and age; once a frame's deadline passes before it reaches a device
  slot it is shed (counted per session) rather than processed — device
  time is never spent on a result the client's latency budget has
  already written off. Undersubscribed systems never shed: every frame
  makes the next batch.
- **EDF/cost across buckets.** A multi-signature frontend groups
  sessions into signature buckets, each with its own compiled program;
  one tick serves ONE bucket (one program launch). ``select_bucket``
  scores every bucket with pending work by *deadline headroom ÷
  measured per-bucket tick cost* and serves the lowest score: a bucket
  whose earliest deadline is closest relative to how long its program
  takes to run is the one most at risk of shedding. Costs are
  MEASURED, never guessed (TVM's measured-stage discipline): the
  compile-time ``Engine.step_block_ms`` calibration seeds the estimate
  and an EWMA over observed batch wall times keeps it current — a
  starved small bucket's headroom shrinks every tick while the big
  bucket's stays refreshed, so the small bucket always wins before its
  deadline passes (fairness pinned in tests/test_multitenant.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from dvf_tpu.serve.session import Slot, StreamSession


@dataclasses.dataclass
class BatchPlan:
    """One tick's device batch: how many rows are real, the
    (session, frame_index) tag per valid row, and — on the monolithic
    staging path only — the staged host array (the streamed ingest path
    stages straight into per-shard slabs, so ``batch`` is None there and
    the router never needs it)."""

    batch: Optional[np.ndarray]
    valid: int
    slots: List[Slot]
    dead: bool = False  # set by supervisor recovery (or a discard) when
    #   the plan's claims were already released — a late result/second
    #   discard for a dead plan must not double-account the sessions
    bucket: Any = None  # the signature bucket this batch belongs to
    #   (serve.server._Bucket): the collect side fetches through that
    #   bucket's egress fetcher and attributes tick cost / faults to it;
    #   None on the legacy single-signature paths (tests, ad-hoc plans)
    cost_sample: bool = True  # False when other batches were in flight
    #   at submit: the submit→materialize wall then includes queue wait
    #   behind THEIR device time, which would contaminate the bucket's
    #   per-program tick-cost EWMA (the EDF/cost denominator) toward the
    #   shared pipeline latency instead of this program's cost
    lin_marks: Any = None  # lineage-armed frontends: the BATCH-level
    #   (component, wall_ts) marks shared by every slot in this batch —
    #   assemble_h2d at dispatch, device/d2h at collect; the router
    #   extends each slot's FrameLineage with them before demux (one
    #   stamp per batch, not per frame). None = lineage off.
    audit_rows: Any = None  # audit-armed frontends (obs.audit): rows
    #   the shadow-replay sampler picked this tick — [(row, input-copy,
    #   session_id, frame_index, lineage), ...]; the collect side pairs
    #   each with its DELIVERED output and hands the pair to the replay
    #   worker. None = audit off or nothing sampled (zero cost).
    fetcher: Any = None  # the egress fetcher THIS batch was prefetched
    #   into, pinned at dispatch: a hot program swap may replace
    #   ``bucket.fetcher`` (new output signature) while this batch is
    #   still in flight, and the collect side must fetch from the one
    #   the D2H was actually issued on. None = monolithic egress (the
    #   collect side falls back to np.asarray).


class ContinuousBatcher:
    """Drains ready frames across sessions into fixed-signature batches."""

    def __init__(self, batch_size: int, staging_pool: int = 2):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = batch_size
        # Bounded internal staging ring for plan() callers that pass no
        # buffer: a fresh multi-MB np.empty per tick put the allocator on
        # the serving hot path. Cycled like the pipeline's per-slot pool;
        # callers that hold a plan across more than ``staging_pool``
        # ticks must pass their own staging (the frontend does).
        self._staging_pool = max(1, staging_pool)
        self._staging: Optional[List[np.ndarray]] = None
        self._staging_seq = 0

    def select(self, sessions: Sequence[StreamSession],
               now: float, pre_drained: bool = False,
               limit: Optional[int] = None) -> Optional[List[Slot]]:
        """Tier-then-EDF slot selection for one batch; None = nothing
        to do.

        Drains every session's ingress, sheds blown deadlines, picks the
        ``batch_size`` earliest-deadline slots, and claims them in-flight
        — everything plan() does except touching frame bytes, so the
        streamed assembler can stage the chosen frames straight into its
        per-shard slabs. Dispatch-thread only: touches the sessions'
        scheduler-owned ``pending`` staging. ``pre_drained`` skips the
        drain/shed pass (select_bucket already ran it this tick);
        ``limit`` overrides ``batch_size`` for this pick (the control
        plane's per-bucket batch sizing).
        """
        candidates: List[Slot] = []
        for s in sessions:
            if not pre_drained:
                s.drain_ingress()
                s.shed_expired(now)  # counted on the session (stats() sums)
            candidates.extend(s.pending)
        if not candidates:
            return None
        # Priority tier first, then EDF within a tier: with spare slots
        # every queued frame makes the batch regardless of tier, so this
        # only bites when OVERSUBSCRIBED — then lower-priority (higher
        # tier value) frames lose the slot race, age, and shed first;
        # paid/interactive sessions shed last by construction. Stable
        # sort + per-session monotonic deadlines (a hard guarantee —
        # submit clamps each deadline to at least the previous one,
        # whatever client ts says) + per-session constant tier ⇒ the
        # chosen set is a prefix of each session's pending deque, so
        # popleft below removes exactly the chosen slots.
        candidates.sort(
            key=lambda slot: (slot.session.config.tier, slot.deadline))
        chosen = candidates[: (limit if limit is not None
                               else self.batch_size)]
        taken_per_session: dict = {}
        for slot in chosen:
            taken_per_session[slot.session] = (
                taken_per_session.get(slot.session, 0) + 1)
        for s, n in taken_per_session.items():
            for _ in range(n):
                s.pending.popleft()
            s.claim_inflight(n)
        return chosen

    def select_bucket(
        self,
        bucket_sessions: Sequence[Tuple[Any, Sequence[StreamSession]]],
        now: float,
    ) -> Tuple[Any, Optional[List[Slot]]]:
        """EDF/cost-aware bucket pick for one tick; ``(None, None)`` =
        nothing to do anywhere.

        ``bucket_sessions``: ``[(bucket, sessions)]`` where ``bucket``
        exposes ``tick_cost_estimate() -> ms`` (a MEASURED per-batch
        cost — Engine.step_block_ms seed + live EWMA). Every bucket's
        ingress is drained and its blown deadlines shed each tick (a
        losing bucket must still age and shed); then buckets with
        pending work are picked by ``(best pending tier, (earliest
        deadline − now) ÷ tick cost)``: priority tier first — a bucket
        holding a tier-0 frame beats any bucket whose best is tier 1+,
        else the within-bucket tier-EDF guarantee silently dissolves
        the moment sessions span buckets (exactly what the quality
        controller's downshift buckets create: under a re-admission
        flood, cost-weighted EDF alone serves interactive only once its
        frames have burned down to the flood's headroom-per-cost) —
        then lowest score wins within a tier: least headroom per unit
        of program time is the bucket most at risk. The winner's slots
        are then claimed by the ordinary within-bucket EDF
        :meth:`select`.
        """
        best = None
        best_key = None
        best_sessions: Optional[Sequence[StreamSession]] = None
        for bucket, sessions in bucket_sessions:
            earliest = None
            tier = None
            for s in sessions:
                s.drain_ingress()
                s.shed_expired(now)
                if s.pending:
                    d = s.pending[0].deadline
                    earliest = d if earliest is None else min(earliest, d)
                    t = s.config.tier
                    tier = t if tier is None else min(tier, t)
            if earliest is None:
                continue
            cost_ms = max(float(bucket.tick_cost_estimate()), 1e-3)
            key = (tier, (earliest - now) * 1e3 / cost_ms)
            if best_key is None or key < best_key:
                best, best_key, best_sessions = bucket, key, sessions
        if best is None:
            return None, None
        # Per-bucket batch size (control plane autotune): a small bucket
        # runs small batches instead of inheriting the frontend-wide
        # batch_size and padding the difference with repeated rows.
        limit = getattr(best, "batch_size", None)
        return best, self.select(best_sessions, now, pre_drained=True,
                                 limit=limit)

    def _pool_staging(self, frame: np.ndarray) -> np.ndarray:
        shape = (self.batch_size, *frame.shape)
        if self._staging is None or self._staging[0].shape != shape \
                or self._staging[0].dtype != frame.dtype:
            self._staging = [np.empty(shape, dtype=frame.dtype)
                             for _ in range(self._staging_pool)]
        self._staging_seq += 1
        return self._staging[self._staging_seq % len(self._staging)]

    def plan(
        self,
        sessions: Sequence[StreamSession],
        now: float,
        staging: Optional[np.ndarray] = None,
    ) -> Optional[BatchPlan]:
        """Assemble one monolithic batch from everything ready; None =
        nothing to do.

        ``staging``: preallocated (batch_size, H, W, C) buffer to fill
        (the frontend's per-inflight-slot pool); the batcher's own
        bounded ring is used when omitted (tests, ad-hoc callers).
        """
        chosen = self.select(sessions, now)
        if chosen is None:
            return None
        valid = len(chosen)
        if staging is None:
            staging = self._pool_staging(chosen[0].frame)
        for row, slot in enumerate(chosen):
            np.copyto(staging[row], slot.frame)
            slot.frame = None  # drop the client's buffer reference
        for row in range(valid, self.batch_size):
            np.copyto(staging[row], staging[valid - 1])
        return BatchPlan(batch=staging, valid=valid, slots=chosen)
