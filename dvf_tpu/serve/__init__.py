"""Multi-stream serving frontend: N tenant streams, one shared engine.

The layer between the single-stream pipeline (`runtime.pipeline`) and
the north star's many-clients workload: per-stream sessions with their
own index space, ingress bound, and latency SLO (`serve.session`); a
continuous cross-session batcher with EDF scheduling and SLO-headroom
shedding (`serve.batcher`); the admission-controlled front door with the
in-process open/submit/poll/close API and the reference-wire ZMQ bridge
(`serve.server`); and the result router that demultiplexes shared
batches back to per-session reorder buffers (`serve.router`).
"""

from dvf_tpu.serve.batcher import BatchPlan, ContinuousBatcher
from dvf_tpu.serve.router import ResultRouter
from dvf_tpu.serve.server import ServeConfig, ServeFrontend, ZmqStreamBridge
from dvf_tpu.serve.session import (
    AdmissionError,
    Delivery,
    ServeError,
    SessionClosedError,
    SessionConfig,
    StreamSession,
)

__all__ = [
    "AdmissionError",
    "BatchPlan",
    "ContinuousBatcher",
    "Delivery",
    "ResultRouter",
    "ServeConfig",
    "ServeError",
    "ServeFrontend",
    "SessionClosedError",
    "SessionConfig",
    "StreamSession",
    "ZmqStreamBridge",
]
