"""Result router: demultiplex shared-engine batches back to sessions.

The collect side of the serving frontend. A completed device batch
carries frames from several tenants interleaved in slot order; the
router walks the plan's ``(session, frame_index)`` tags, feeds each valid
row to its session's reorder buffer, advances that session's display
cursor, and emits whatever became ready to the session's out queue or
sink. The padded tail rows (``row >= valid``) are dropped exactly like
the single-stream collect path.

Observability stays session-local here: every delivered frame is
recorded in its session's ``LatencyStats``; the frontend-wide p50/p99
export merges those per-stream samples on demand
(``LatencyStats.merged``), so nothing is recorded twice.
"""

from __future__ import annotations

import threading

import numpy as np

from dvf_tpu.serve.batcher import BatchPlan


class ResultRouter:
    """Collect-thread component: batches in, per-session deliveries out."""

    def __init__(self):
        self.batches = 0
        self.frames = 0
        self.late_after_close = 0  # results for hard-closed sessions
        self.late_after_recovery = 0  # results for plans the supervisor
        #   already wrote off (their sessions' claims were released at
        #   recovery; routing them now would double-account)
        self._dead_lock = threading.Lock()  # makes the plan.dead
        #   check-then-set atomic: recovery (supervisor thread) and a
        #   waking superseded collect thread may discard the same plan
        #   concurrently, and a double discard_inflight would drive
        #   session.inflight negative

    def route(self, plan: BatchPlan, out: np.ndarray) -> int:
        """Demux one completed batch; returns frames delivered.

        Rows are copied out of the batch array: a view would keep the
        whole (batch_size, H, W, C) result alive for as long as ONE
        delivery sits unpolled — a slow-polling client could pin
        out_queue_size full batches (batch_size× amplification) instead
        of out_queue_size frames.
        """
        with self._dead_lock:
            if plan.dead:
                self.late_after_recovery += 1
                return 0
            plan.dead = True  # consumed — a recovery discard racing this
            #   route (the plan was still in the supervisor window) must
            #   become a no-op, not a second release of the same claims
        touched = []
        marks = plan.lin_marks
        for row, slot in enumerate(plan.slots[: plan.valid]):
            s = slot.session
            if slot.lin is not None and marks:
                # Batch-level hop stamps (assemble_h2d / device / d2h)
                # fan out to every slot's lineage here — the one place
                # each routed row already passes.
                slot.lin.marks.extend(marks)
            s.complete(slot, out[row].copy())
            if s.state == "closed":
                self.late_after_close += 1
            elif s not in touched:
                touched.append(s)
        delivered = 0
        for s in touched:
            delivered += s.deliver_ready()
        self.batches += 1
        self.frames += plan.valid
        if plan.bucket is not None:
            # Lifetime per-bucket row counter, maintained HERE (the one
            # place every routed row passes) so the bucket's export
            # stays monotone across session retirement — a per-session
            # sum would shrink when a tenant retires, which a counter
            # consumer reads as a reset.
            plan.bucket.routed_frames += plan.valid
        return delivered

    def discard(self, plan: BatchPlan, kind: str = None) -> None:
        """A device batch failed; release its sessions' in-flight claims
        so a closing session can still finalize. ``kind`` (a FaultKind)
        attributes the loss in each session's per-kind fault counters;
        None for non-fault discards (shutdown). Idempotent: a plan
        already written off (supervisor recovery) is skipped."""
        with self._dead_lock:
            if plan.dead:
                return
            plan.dead = True
        per_session = {}
        for slot in plan.slots[: plan.valid]:
            per_session[slot.session] = per_session.get(slot.session, 0) + 1
        for s, n in per_session.items():
            s.discard_inflight(n, kind=kind)

    def stats(self) -> dict:
        return {
            "batches": self.batches,
            "frames": self.frames,
            "late_after_close": self.late_after_close,
            "late_after_recovery": self.late_after_recovery,
        }
