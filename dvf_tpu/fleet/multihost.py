"""The BIGGER-replica flavor: one replica spanning a process group.

:class:`MultiHostReplica` is the fleet handle for a replica whose
worker is a ``MultiHostEngine`` process group — ``hosts`` child
processes joined by ``jax.distributed`` (gloo collectives on CPU, ICI
on a real pod), compiling ONE pjit program across every member's
devices and serving it behind the standard replica RPC. The fleet
router cannot tell it from a :class:`~dvf_tpu.fleet.replica.
ProcessReplica`: same transport, same health/stats surface, same
drain/migrate/restart supervision — a peer loss inside the group makes
the LEADER unhealthy and the whole group is replaced as a unit
(replica-granular loss, the router's existing domain; intra-group
elasticity is `parallel.distributed.ElasticMeshRunner` territory).

This is the elasticity controller's second axis (ROADMAP item 2's last
leg): when the measured stage profiles say one host's device time IS
the latency, ``scale_out`` targets this flavor instead of another
single-host replica — more devices under one program, not more queues.

A multihost replica serves ONE signature, fixed at spawn (the fleet
pins it to the first ``--precompile`` manifest entry): the group
compiles one program in lockstep, and re-pointing it is a respawn.
Leader/peer wiring lives in ``fleet._mh_worker``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional

from dvf_tpu.fleet.replica import _LIVE_PROCS, ProcessReplica


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class MultiHostReplica(ProcessReplica):
    """Process-group replica behind the standard replica RPC (module
    docstring). Reuses ProcessReplica's whole client side — handshake,
    serial channel, bounded health/stats probes, clock-offset estimate
    — and overrides only the spawn/teardown to manage ``hosts``
    processes instead of one."""

    def __init__(
        self,
        replica_id: str,
        op_chain: str,
        frame_shape: tuple,
        frame_dtype: str = "uint8",
        hosts: int = 2,
        batch_size: int = 8,
        slo_ms: float = 1000.0,
        queue_size: int = 64,
        out_queue_size: int = 1024,
        env: Optional[Dict[str, str]] = None,
        startup_timeout_s: float = 180.0,
        rpc_timeout_s: float = 60.0,
    ):
        if hosts < 2:
            raise ValueError("a multihost replica needs hosts >= 2")
        # The global batch must divide evenly across the group: a
        # non-divisible batch axis replicates (every host feeds every
        # row), which defeats the sharding the flavor exists for.
        batch_global = max(1, batch_size // hosts) * hosts
        self.hosts = hosts
        self.mh_config = {
            "op_chain": op_chain,
            "frame_shape": [int(d) for d in frame_shape],
            "frame_dtype": str(frame_dtype),
            "batch_global": batch_global,
            "slo_ms": float(slo_ms),
            "queue_size": int(queue_size),
            "out_queue_size": int(out_queue_size),
            "hosts": hosts,
        }
        self._group: List[subprocess.Popen] = []
        super().__init__(
            replica_id,
            wire_config={"mh": dict(self.mh_config)},
            env=env,
            startup_timeout_s=startup_timeout_s,
            rpc_timeout_s=rpc_timeout_s,
        )

    # -- group spawn/teardown (the ProcessReplica seams) -----------------

    def _launch(self, port: int) -> subprocess.Popen:
        coordinator_port = _free_port()
        peer_port = _free_port()
        env = self._child_env()
        env["DVF_MH_CONFIG"] = json.dumps(self.mh_config)
        stderr = (None
                  if os.environ.get("DVF_FLEET_WORKER_STDERR") == "1"
                  else subprocess.DEVNULL)
        self._group = []
        leader = None
        for pid in range(self.hosts):
            p = subprocess.Popen(
                [sys.executable, "-m", "dvf_tpu.fleet._mh_worker",
                 "--parent-port", str(port),
                 "--peer-port", str(peer_port),
                 "--coordinator", f"127.0.0.1:{coordinator_port}",
                 "--num-processes", str(self.hosts),
                 "--process-id", str(pid),
                 "--replica-id", self.id],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=stderr,
                close_fds=False,
            )
            self._group.append(p)
            _LIVE_PROCS.add(p)
            if pid == 0:
                leader = p
        return leader

    def _sweep_group(self, timeout: float) -> None:
        """Reap every group member (the leader's stop already asked
        peers to exit; a wedged one is killed)."""
        group, self._group = self._group, []
        for p in group:
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass

    def stop(self, timeout: float = 10.0) -> None:
        super().stop(timeout=timeout)
        self._sweep_group(timeout=min(timeout, 5.0))

    def kill(self) -> None:
        super().kill()
        for p in self._group:
            try:
                p.kill()
            except OSError:
                pass

    def alive(self) -> bool:
        # The group lives and dies as a unit: any member's death is the
        # replica's (the leader's next collective would wedge — don't
        # wait for it).
        return bool(not self._lost and self._group
                    and all(p.poll() is None for p in self._group))
