"""Replica manager: one handle per engine replica, two transports.

A *replica* is one complete ``serve.ServeFrontend`` (its own Engine, its
own dispatch/collect threads, its own fault budgets and watchdog). The
fleet router (`fleet.router`) talks to replicas only through the
:class:`ReplicaHandle` interface defined here, so the same routing /
affinity / drain logic runs over both transports:

:class:`LocalReplica`
    The frontend lives in this process, on a device *slice* of the local
    mesh (N replicas partition ``jax.devices()``). Zero IPC cost — the
    mode for single-process deployments, unit tests, and TPU hosts where
    all replicas share one PJRT client.

:class:`ProcessReplica`
    The frontend lives in a child process (``fleet._worker``) with its
    own jax runtime, reached over a length-prefixed pickle RPC on a
    localhost socket. This is the scale-out shape: replica loss is a real
    process death, replica restart is a real respawn, and on CPU each
    replica owns its own cores/GIL — the configuration the fleet scaling
    bench measures. A replica that should span *hosts* runs the
    multi-process engine path (`fleet.multiproc.MultiHostEngine`) inside
    its worker process, with the other hosts joining via
    ``jax.distributed``.

Every RPC failure (socket error, timeout, dead process) surfaces as
:class:`ReplicaLostError`; the router classifies it as a ``replica``
fault and runs the drain → migrate → restart procedure. Handles are
transport only: session placement and health policy live in the router.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from dvf_tpu.serve.session import (
    AdmissionError,
    ServeError,
    SessionClosedError,
)

# Replica lifecycle states (fleet-owned; the handle just stores them).
HEALTHY, DRAINING, RESTARTING, DEAD = (
    "healthy", "draining", "restarting", "dead")

# Live replica child processes, for the session-end leak guard in
# tests/conftest.py: a fleet test that leaks a worker process would
# otherwise keep a whole jax runtime alive past the suite.
_LIVE_PROCS: "weakref.WeakSet" = weakref.WeakSet()


class ReplicaLostError(ServeError):
    """The replica's process/channel is gone (or it timed out) — the
    fleet tier's signal to drain, migrate, and restart."""


def pid_alive(pid: int) -> bool:
    """Signal-0 liveness probe — what an ADOPTED replica (continuity
    plane: the front door restarted, the worker didn't) has instead of
    a ``Popen`` to poll."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True      # exists, just not ours to signal
    except OSError:
        return False
    return True


# -- wire protocol (ProcessReplica <-> fleet._worker) --------------------

def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 4)
    (n,) = struct.unpack("!I", header)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the replica channel")
        buf.extend(chunk)
    return bytes(buf)


# Exceptions that cross the RPC boundary by NAME (worker sends
# ("err", type_name, message); the parent re-raises the mapped type so
# fleet admission/session semantics survive the process hop).
_WIRE_ERRORS = {
    "AdmissionError": AdmissionError,
    "SessionClosedError": SessionClosedError,
    "ServeError": ServeError,
    "KeyError": KeyError,
    "ValueError": ValueError,
}


def raise_wire_error(type_name: str, message: str) -> None:
    exc_type = _WIRE_ERRORS.get(type_name, ServeError)
    if exc_type is KeyError:
        raise KeyError(message)
    raise exc_type(f"{message}" if exc_type is not ServeError
                   else f"[{type_name}] {message}")


# -- handle interface ----------------------------------------------------

class ReplicaHandle:
    """Transport-agnostic view of one replica (see module docstring)."""

    def __init__(self, replica_id: str):
        self.id = replica_id
        self.state = DEAD          # until start() succeeds
        self.restarts = 0
        self.started_at: Optional[float] = None
        self.clock_offset_s = 0.0  # replica wall clock − front-door
        #   wall clock: what frame-lineage marks crossing this replica's
        #   boundary are re-based by (obs.lineage.FrameLineage.rebase —
        #   the merge_tracer_snapshots epoch discipline, per frame).
        #   Exactly 0 for in-process replicas; process replicas estimate
        #   it from the health RPC's midpoint each monitor tick.

    # lifecycle
    def start(self) -> "ReplicaHandle":
        raise NotImplementedError

    def stop(self, timeout: float = 10.0) -> None:
        raise NotImplementedError

    def restart(self) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        """Hard loss, for chaos/tests: the replica becomes unreachable
        NOW (process replicas die for real)."""
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    # serving ops (any may raise ReplicaLostError)
    def open_stream(self, session_id, slo_ms=None, frame_shape=None,
                    frame_dtype=None, op_chain=None, tier=None) -> str:
        raise NotImplementedError

    def submit(self, session_id, frame, ts=None, tag=None) -> None:
        """Enqueue one frame. No return value by contract: the fleet
        assigns indices itself, and the process transport is one-way on
        this path (see ProcessReplica._send_only)."""
        raise NotImplementedError

    def poll(self, session_id, max_items=None, meta_only=False) -> list:
        raise NotImplementedError

    def close(self, session_id, drain=True) -> None:
        raise NotImplementedError

    def release(self, session_id) -> None:
        raise NotImplementedError

    def drain(self, timeout: float = 30.0) -> bool:
        raise NotImplementedError

    def begin_drain(self) -> None:
        """Replica-side admission off (``ServeFrontend.begin_drain``):
        the first half of a graceful retire — the fleet stops placing
        there anyway (state flips out of HEALTHY), but the replica's own
        gate closing too means a raced direct open cannot slip in."""
        raise NotImplementedError

    def health(self) -> dict:
        """Liveness + the replica's cheap ``load`` row (queue depth,
        occupancy, monotone counters, p99 — ``ServeFrontend.load_row``):
        what the fleet monitor caches for the RPC-free elastic view."""
        raise NotImplementedError

    def stats_full(self) -> dict:
        """{"stats": frontend.stats(), "latency": latency_snapshot(),
        "health": health()} — one RPC for the whole export."""
        raise NotImplementedError

    def trace_snapshot(self) -> dict:
        """The replica frontend's ``Tracer.snapshot()`` — its bounded
        event window plus wall-clock epoch, the unit the fleet merges
        into ONE Perfetto session (``obs.trace.merge_tracer_snapshots``).
        Plain pickle-safe values, so the same export crosses the process
        RPC unchanged."""
        raise NotImplementedError

    def audit_probe(self, signature=None) -> dict:
        """Run the audit plane's deterministic probe frame through this
        replica's compiled program for ``signature`` and return
        ``{"signature", "digest"}`` (``ServeFrontend.audit_probe``) —
        the fleet's cross-replica divergence detector compares these
        across replicas warm on the same signature."""
        raise NotImplementedError


class LocalReplica(ReplicaHandle):
    """In-process replica: a ServeFrontend over a device slice."""

    def __init__(self, replica_id: str, frontend_factory):
        super().__init__(replica_id)
        self._make = frontend_factory   # () -> started ServeFrontend
        self.frontend = None
        self._lost = False

    def start(self) -> "LocalReplica":
        self.frontend = self._make()
        self._lost = False
        self.state = HEALTHY
        self.started_at = time.monotonic()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        fe, self.frontend = self.frontend, None
        self.state = DEAD
        if fe is not None:
            try:
                fe.stop(timeout=timeout)
            except Exception:  # noqa: BLE001 — teardown best-effort; a
                pass           # failed replica's stored error re-raises

    def restart(self) -> None:
        self.stop(timeout=2.0)
        self.start()
        self.restarts += 1  # on success only (see ProcessReplica)

    def kill(self) -> None:
        # Simulated hard loss: ops fail from now on; the abandoned
        # frontend is torn down best-effort (unlike a process kill there
        # is no OS to reap its threads for us). Lifecycle state is NOT
        # touched — the router's monitor owns it: it must still see this
        # replica as one whose loss needs handling.
        self._lost = True
        fe, self.frontend = self.frontend, None
        if fe is not None:
            try:
                fe.stop(timeout=2.0)
            except Exception:  # noqa: BLE001 — it is being abandoned
                pass

    def alive(self) -> bool:
        return (not self._lost and self.frontend is not None
                and self.frontend._error is None)

    def _fe(self):
        if self._lost or self.frontend is None:
            raise ReplicaLostError(f"replica {self.id} is lost")
        return self.frontend

    def open_stream(self, session_id, slo_ms=None, frame_shape=None,
                    frame_dtype=None, op_chain=None, tier=None) -> str:
        return self._fe().open_stream(
            session_id=session_id, slo_ms=slo_ms,
            frame_shape=frame_shape, frame_dtype=frame_dtype,
            op_chain=op_chain, tier=tier)

    def submit(self, session_id, frame, ts=None, tag=None) -> int:
        return self._fe().submit(session_id, frame, ts=ts, tag=tag)

    def poll(self, session_id, max_items=None, meta_only=False) -> list:
        got = self._fe().poll(session_id, max_items)
        if meta_only:
            got = [d._replace(frame=None) for d in got]
        return got

    def close(self, session_id, drain=True) -> None:
        self._fe().close(session_id, drain=drain)

    def release(self, session_id) -> None:
        self._fe().release(session_id)

    def drain(self, timeout: float = 30.0) -> bool:
        return self._fe().drain(timeout=timeout)

    def begin_drain(self) -> None:
        self._fe().begin_drain()

    def health(self) -> dict:
        fe = self._fe()
        return dict(fe.health(), load=fe.load_row())

    def stats_full(self) -> dict:
        fe = self._fe()
        return {"stats": fe.stats(), "latency": fe.latency_snapshot(),
                "signals": fe.signals(), "health": fe.health()}

    def trace_snapshot(self) -> dict:
        return self._fe().tracer.snapshot()

    def audit_probe(self, signature=None) -> dict:
        return self._fe().audit_probe(signature)


class ProcessReplica(ReplicaHandle):
    """Replica in a child process, reached over the pickle RPC.

    ``wire_config`` is the dict ``fleet._worker`` builds its frontend
    from: ``{"replica_id", "filter": (name, kwargs), "serve": {simple
    ServeConfig fields}, "chaos_spec", "chaos_seed"}`` — specs, not
    objects, because filters (closures) and armed FaultPlans (locks)
    don't pickle. Each replica parses its OWN chaos plan, so event
    streams stay deterministic per replica.
    """

    def __init__(
        self,
        replica_id: str,
        wire_config: dict,
        env: Optional[Dict[str, str]] = None,
        startup_timeout_s: float = 120.0,
        rpc_timeout_s: float = 60.0,
        rpc_op_timeout_s: float = 5.0,
        rpc_lock_timeout_s: float = 5.0,
    ):
        super().__init__(replica_id)
        self._wire_config = dict(wire_config, replica_id=replica_id)
        self._env = dict(env) if env is not None else None
        self._startup_timeout_s = startup_timeout_s
        self._rpc_timeout_s = rpc_timeout_s
        # Bounded control-plane RPCs (health, begin_drain, stats pulls):
        # previously hardcoded 5.0s constants — promoted to knobs
        # (FleetConfig.rpc_op_timeout_s / rpc_lock_timeout_s) so slow
        # deployments can widen the monitor's patience, and exported in
        # the fleet's stats()["fleet"] provenance.
        self._rpc_op_timeout_s = rpc_op_timeout_s
        self._rpc_lock_timeout_s = rpc_lock_timeout_s
        self._proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._lost = False
        self.pid: Optional[int] = None
        self.reattach_port: Optional[int] = None  # the worker's own
        #   listener for front-door crash recovery (continuity plane);
        #   None when the worker predates it or the grace is unarmed

    def _child_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        # The child defaults to ONE device and no test-harness device
        # forcing: a replica's parallelism is its own mesh's business
        # (override via the env dict for multi-device replicas).
        env["XLA_FLAGS"] = ""
        env.pop("JAX_NUM_CPU_DEVICES", None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        if self._env:
            env.update(self._env)
        return env

    def _launch(self, port: int) -> subprocess.Popen:
        """Spawn the worker process(es); returns the one that dials the
        parent RPC listener. The seam the multi-host flavor overrides
        (`fleet.multihost.MultiHostReplica` spawns a whole
        jax.distributed group and returns its leader)."""
        return subprocess.Popen(
            [sys.executable, "-m", "dvf_tpu.fleet._worker",
             "--port", str(port), "--replica-id", self.id],
            env=self._child_env(),
            stdout=subprocess.DEVNULL,
            stderr=(None
                    if os.environ.get("DVF_FLEET_WORKER_STDERR") == "1"
                    else subprocess.DEVNULL),
            # close_fds=False keeps posix_spawn eligible: a restart
            # from a large parent (a loaded test suite, a long-lived
            # server) must not have to FORK the whole address space
            # just to exec a worker — observed as transient respawn
            # failures under memory pressure. The worker dials its
            # own socket and ignores inherited fds.
            close_fds=False,
        )

    def start(self) -> "ProcessReplica":
        listener = socket.socket()
        try:
            listener.bind(("127.0.0.1", 0))
            listener.listen(1)
            listener.settimeout(self._startup_timeout_s)
            port = listener.getsockname()[1]
            self._proc = self._launch(port)
            _LIVE_PROCS.add(self._proc)
            try:
                self._sock, _ = listener.accept()
            except socket.timeout:
                raise ReplicaLostError(
                    f"replica {self.id}: worker never connected within "
                    f"{self._startup_timeout_s:.0f}s (spawn failed?)")
        finally:
            listener.close()
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(self._startup_timeout_s)
        hello = recv_msg(self._sock)
        if not (isinstance(hello, tuple) and hello[0] == "hello"):
            raise ReplicaLostError(f"replica {self.id}: bad hello {hello!r}")
        self.pid = hello[1]
        send_msg(self._sock, ("config", self._wire_config))
        ready = recv_msg(self._sock)
        if not (isinstance(ready, tuple) and ready[0] == "ready"):
            raise ReplicaLostError(
                f"replica {self.id}: worker failed to start: {ready!r}")
        # Trailing extras dict since the continuity plane (the worker's
        # reattach listener port); a 2-tuple from an older worker still
        # reads as ready, just never adoptable.
        extras = ready[2] if len(ready) > 2 and isinstance(ready[2], dict) \
            else {}
        self.reattach_port = extras.get("reattach_port")
        self._sock.settimeout(self._rpc_timeout_s)
        self._lost = False
        self.state = HEALTHY
        self.started_at = time.monotonic()
        return self

    def adopt(self, pid: int, reattach_port: int) -> "ProcessReplica":
        """Re-attach to a still-running worker left behind by a crashed
        front door (continuity plane): dial the worker's own reattach
        listener instead of spawning. No ``Popen`` exists for an
        adopted child — liveness degrades to a signal-0 probe and stop
        falls back to a pid wait + SIGKILL."""
        sock = socket.create_connection(
            ("127.0.0.1", int(reattach_port)),
            timeout=min(self._startup_timeout_s, 10.0))
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(min(self._startup_timeout_s, 10.0))
            send_msg(sock, ("adopt", self.id))
            reply = recv_msg(sock)
            if not (isinstance(reply, tuple) and reply[0] == "adopted"):
                raise ReplicaLostError(
                    f"replica {self.id}: adoption refused: {reply!r}")
        except Exception:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self.pid = int(pid)
        self.reattach_port = int(reattach_port)
        self._proc = None
        self._sock = sock
        self._sock.settimeout(self._rpc_timeout_s)
        self._lost = False
        self.state = HEALTHY
        self.started_at = time.monotonic()
        return self

    def abandon(self) -> None:
        """Front-door crash simulation (FleetFrontend.crash): drop the
        RPC channel and FORGET the child without a stop op — the worker
        sees a parent loss and waits on its reattach listener for the
        next front-door incarnation to adopt it."""
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._proc = None
        self.state = DEAD

    def stop(self, timeout: float = 10.0) -> None:
        self.state = DEAD
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.settimeout(min(timeout, self._rpc_op_timeout_s))
                send_msg(sock, ("stop",))
                recv_msg(sock)
            except Exception:  # noqa: BLE001 — it may already be dead
                pass
            try:
                sock.close()
            except OSError:
                pass
        proc, self._proc = self._proc, None
        if proc is not None:
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        elif self.pid is not None:
            # Adopted child: no Popen to reap — wait for the pid to
            # exit on its own stop, then escalate to SIGKILL. When the
            # worker is OUR child (in-process crash simulation: the
            # same process abandoned and re-adopted it), it zombifies
            # until reaped, and a zombie still answers signal 0 — so
            # try waitpid first and fall back to the signal-0 probe for
            # true cross-process adoption (init reaps that one).
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    done, _ = os.waitpid(self.pid, os.WNOHANG)
                    if done == self.pid:
                        return
                except ChildProcessError:
                    if not pid_alive(self.pid):
                        return
                except OSError:
                    return
                time.sleep(0.05)
            try:
                os.kill(self.pid, 9)
            except OSError:
                pass

    def restart(self) -> None:
        self.stop(timeout=5.0)
        self.start()
        self.restarts += 1  # counted on SUCCESS only: the router's
        #   restart budget bounds replica loss events, not respawn
        #   attempts that never produced a replica

    def kill(self) -> None:
        # Real hard loss (state untouched — the router's monitor owns
        # lifecycle and must still handle this as a fresh loss).
        self._lost = True
        if self._proc is not None:
            try:
                self._proc.kill()
            except OSError:
                pass
        elif self.pid is not None:   # adopted child: kill by pid
            try:
                os.kill(self.pid, 9)
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def alive(self) -> bool:
        if self._lost:
            return False
        if self._proc is not None:
            return self._proc.poll() is None
        # Adopted child (no Popen): the connected RPC socket plus a
        # signal-0 probe stand in for poll().
        return (self._sock is not None and self.pid is not None
                and pid_alive(self.pid))

    def _rpc(self, op: Tuple, timeout: Optional[float] = None,
             lock_timeout: Optional[float] = None) -> Any:
        # The channel lock serializes ops on the one socket. A bounded
        # lock_timeout keeps the health monitor's short-timeout probe
        # honest: a submit's sendall against a non-draining worker can
        # hold the lock for up to rpc_timeout_s, and the monitor must
        # not be wedged behind it (a busy channel reads as "try next
        # tick", not as replica loss — the blocked submit itself will
        # classify a truly dead worker within its own socket timeout).
        if lock_timeout is not None:
            if not self._lock.acquire(timeout=lock_timeout):
                raise TimeoutError(
                    f"replica {self.id}: channel busy for "
                    f"{lock_timeout:.1f}s (op {op[0]!r} skipped)")
        else:
            self._lock.acquire()
        try:
            if self._lost or self._sock is None:
                raise ReplicaLostError(f"replica {self.id} is lost")
            try:
                if timeout is not None:
                    self._sock.settimeout(timeout)
                send_msg(self._sock, op)
                reply = recv_msg(self._sock)
            except (OSError, ConnectionError, EOFError,
                    pickle.UnpicklingError) as e:
                self._lost = True
                raise ReplicaLostError(
                    f"replica {self.id}: RPC {op[0]!r} failed: {e!r}")
            finally:
                if timeout is not None and self._sock is not None:
                    try:
                        self._sock.settimeout(self._rpc_timeout_s)
                    except OSError:
                        pass
        finally:
            self._lock.release()
        if reply[0] == "ok":
            return reply[1]
        if reply[0] == "err":
            raise_wire_error(reply[1], reply[2])
        raise ReplicaLostError(f"replica {self.id}: bad reply {reply[0]!r}")

    def _send_only(self, op: Tuple) -> None:
        """Fire-and-forget op (no reply): the hot submit path. Waiting
        for a reply would serialize every frame on the worker's GIL
        latency (~one thread-switch interval per frame — measured 5 ms,
        an order of magnitude over the wire cost); the socket itself is
        the backpressure — a slow worker fills its buffers and sendall
        blocks. Replica-side errors are counted there and surface
        through ``health()``/``stats`` (``submit_errors``) instead of a
        per-frame ack; frame loss is already accounted by the fleet's
        index-gap arithmetic (submitted − delivered)."""
        with self._lock:
            if self._lost or self._sock is None:
                raise ReplicaLostError(f"replica {self.id} is lost")
            try:
                send_msg(self._sock, op)
            except (OSError, ConnectionError) as e:
                self._lost = True
                raise ReplicaLostError(
                    f"replica {self.id}: send {op[0]!r} failed: {e!r}")

    def open_stream(self, session_id, slo_ms=None, frame_shape=None,
                    frame_dtype=None, op_chain=None, tier=None) -> str:
        # 7-tuple since the control plane (trailing tier); a 6-tuple
        # from an older parent still opens at the worker's default tier.
        return self._rpc(("open", session_id, slo_ms, frame_shape,
                          str(frame_dtype) if frame_dtype is not None
                          else None, op_chain, tier))

    def submit(self, session_id, frame, ts=None, tag=None) -> None:
        self._send_only(("submit1", session_id, frame, ts, tag))

    def poll(self, session_id, max_items=None, meta_only=False) -> list:
        return self._rpc(("poll", session_id, max_items, meta_only))

    def close(self, session_id, drain=True) -> None:
        self._rpc(("close", session_id, drain))

    def release(self, session_id) -> None:
        self._rpc(("release", session_id))

    def drain(self, timeout: float = 30.0) -> bool:
        return self._rpc(("drain", timeout), timeout=timeout + 10.0)

    def begin_drain(self) -> None:
        self._rpc(("begin_drain",), timeout=self._rpc_op_timeout_s,
                  lock_timeout=self._rpc_lock_timeout_s)

    def health(self) -> dict:
        # Short timeouts on BOTH the socket and the channel lock: the
        # monitor polls this at hertz rates and must never sit behind a
        # slow submit for the full RPC budget (TimeoutError = "busy,
        # retry next tick"; liveness and the submit path's own socket
        # timeout still catch real deaths).
        t0 = time.time()
        out = self._rpc(("health",), timeout=self._rpc_op_timeout_s,
                        lock_timeout=self._rpc_lock_timeout_s)
        t1 = time.time()
        if isinstance(out, dict):
            wall = out.get("wall_time_s")
            # RPC-midpoint clock-offset estimate (NTP's trick): the
            # worker stamped its wall clock somewhere inside [t0, t1];
            # the midpoint bounds the error by half the round trip.
            # GATED on that round trip: a health RPC that waited
            # seconds behind a busy submit (the channel lock allows up
            # to 5 s) would poison the offset by up to half that wait,
            # garbling every lineage re-base until the next tick —
            # keep the previous estimate and wait for a clean probe.
            if wall is not None and (t1 - t0) <= 0.25:
                self.clock_offset_s = wall - (t0 + t1) / 2.0
        return out

    def stats_full(self) -> dict:
        # Bounded on the CHANNEL LOCK only: a stats pull queued behind a
        # busy submit degrades to TimeoutError — "no export this tick"
        # at the caller — without touching the socket. The socket keeps
        # the default rpc_timeout_s deliberately: a mid-flight socket
        # timeout desynchronizes the serial channel (the late reply
        # would answer the NEXT request), so it must keep meaning
        # replica loss — and a scrape must not be able to declare a
        # merely-slow replica dead.
        return self._rpc(("stats",),
                         lock_timeout=self._rpc_lock_timeout_s)

    def trace_snapshot(self) -> dict:
        # Same bound discipline as stats_full: busy channel → benign
        # TimeoutError (one skipped lane); socket-level death → loss.
        # Dump pulls run off the monitor/loss paths (router dumps are
        # off-thread), so the worst case blocks a dump thread, not
        # supervision.
        return self._rpc(("trace",),
                         lock_timeout=self._rpc_lock_timeout_s)

    def audit_probe(self, signature=None) -> dict:
        # Bounded like the monitor's health probe: a divergence check
        # runs at the monitor's cadence and must degrade to "replica
        # unprobeable this round" behind a busy submit, never wedge.
        return self._rpc(("audit_probe", signature),
                         lock_timeout=self._rpc_lock_timeout_s)


def live_worker_processes() -> List[subprocess.Popen]:
    """Still-running replica child processes (the conftest leak guard)."""
    return [p for p in list(_LIVE_PROCS) if p.poll() is None]
