"""Fleet-level stats: merge per-replica exports into one view.

Every replica already computes its own half — ``ServeFrontend.stats()``
(per-session rows + per-replica aggregate), ``latency_snapshot()``
(mergeable weighted samples), ``faults.summary()`` (per-kind counters,
replica-attributed via ``ServeConfig.replica_label``). This module does
the other half: the front door pulls those exports (in-process reads or
one ``stats`` RPC per process replica) and folds them into fleet-wide
latency percentiles (``LatencyStats.merge_snapshots`` — weighted raw
samples, never averaged percentiles) and a fleet fault table with
``by_replica`` attribution.
"""

from __future__ import annotations

from typing import Dict, Optional

from dvf_tpu.obs.metrics import LatencyStats
from dvf_tpu.resilience.faults import FaultStats


def merge_fault_summaries(
    fleet_own: dict,
    per_replica: Dict[str, Optional[dict]],
) -> dict:
    """The fleet fault table: the router's own faults (``replica``
    losses it observed, attributed to the replica that died) plus every
    reachable replica's summary. Unreachable replicas contribute nothing
    — their loss is already counted on the fleet side."""
    merged = FaultStats()
    merged.absorb_summary(fleet_own)
    for rid, summary in per_replica.items():
        if summary:
            merged.absorb_summary(summary, replica=rid)
    return merged.summary()


def merge_latency_snapshots(per_replica: Dict[str, Optional[dict]]) -> dict:
    """Fleet p50/p99/fps over replicas' weighted sample snapshots."""
    return LatencyStats.merge_snapshots(
        [s for s in per_replica.values() if s])


def replica_row(handle, export: Optional[dict], sessions: int) -> dict:
    """One replica's row in the fleet stats table: lifecycle + the
    headline numbers from its export (None when unreachable)."""
    row = {
        "state": handle.state,
        "restarts": handle.restarts,
        "sessions": sessions,
    }
    if export is not None:
        st = export.get("stats", {})
        row.update(
            engine_batches=st.get("engine_batches"),
            engine_frames=st.get("engine_frames"),
            open_sessions=st.get("open_sessions"),
            queue_depth=st.get("queue_depth"),
            # The replica's MONOTONE lifetime counter (signals() carries
            # the evicted-session floor) — the scrape's counter source;
            # the windowed aggregate.count beside it is NOT monotone.
            delivered_total=(export.get("signals") or {}).get(
                "delivered_total"),
            errors=st.get("errors"),
            recoveries=st.get("recoveries"),
            faults=st.get("faults", {}).get("by_kind", {}),
            aggregate=st.get("aggregate"),
        )
        attr = st.get("attribution")
        if attr is not None:
            # Lineage-armed replicas: the per-replica latency
            # attribution rides the same stats RPC — the fleet-wide
            # half of "where did my p99 go" (explain() fans this out).
            row["attribution"] = attr
    return row
