"""Fleet admission: signature-aware least-loaded placement + spillover.

The profiling-driven adaptive distributed-inference pattern (PAPERS.md,
arXiv:2605.25682) at the serving layer: new sessions open on the
least-loaded healthy replica; when that replica's own admission gate is
full (``serve``-level ``max_sessions``/``max_buckets``), the open
*spills over* to the next candidate instead of failing; only when EVERY
healthy replica has refused does the fleet reject. Load is the router's
count of sessions it has bound to each replica — a placement heuristic
only; the replica's own gate stays the source of truth, so a stale
count can cost one extra spillover hop, never a wrong admission.

Placement is SIGNATURE-AWARE: a declared ``(op_chain, geometry, dtype)``
open prefers a replica whose program pool is already warm for that
canonical key (its admission is a pool hit — milliseconds, vs a full
trace+compile on a cold one). Warmth is a BOUNDED bias, not an
absolute rank: a warm replica tolerates one session of extra load
(and wins ties) before losing to a colder, emptier candidate —
unbounded warm-first would funnel every session of a uniform-signature
fleet onto one replica and defeat the scaling the fleet exists for,
while zero bias would never route a follow-up open to the replica
that just paid the compile. Cold admits and undeclared opens place
least-loaded-first exactly as before.

Affinity is the other half of placement and is deliberately NOT here:
once a session is bound, every one of its frames goes to that replica
(per-session index monotonicity needs one reorder buffer), so placement
decisions happen only at open and at migration — both route through
:meth:`SpilloverAdmission.candidates`.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence


class SpilloverAdmission:
    """Candidate ordering + admission counters for the fleet router."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spillovers = 0   # opens that fell past their first choice
        self.rejections = 0   # opens refused by every healthy replica
        self.warm_placements = 0  # opens routed by signature warmth
        self.tier_rejections = 0  # low-tier opens refused by the fleet
        #   capacity guard (graceful shed, not a failure)
        self.rejections_by_tier: Dict[int, int] = {}  # every fleet-level
        #   refusal keyed by the refused open's tier — the elasticity
        #   controller's key input was previously visible only in
        #   rejection STRINGS; these counters put it on the telemetry
        #   ring (fleet signals() flattens them per tier name)

    def candidates(
        self,
        replicas: Sequence,                  # ReplicaHandle, .state/.id
        load: Dict[str, int],                # router's sessions-per-replica
        exclude: Optional[Iterable[str]] = None,
        warm: Optional[Dict[str, Iterable[str]]] = None,
        key: Optional[str] = None,
        prefer_packed: bool = False,
    ) -> List:
        """Healthy replicas ranked by warm-biased load (see module
        docstring): effective load = load − 1 for a replica warm for
        ``key``, warmth breaks ties, id makes equal ranks
        deterministic. ``warm`` maps replica id → canonical signature
        renders its pool serves without a compile (from each replica's
        ``health()`` export); ``key`` is the open's canonical signature
        render (None = undeclared → pure least-loaded). ``exclude``
        drops specific ids — migration must not re-place a session on
        the replica it is fleeing.

        ``prefer_packed`` inverts the load rank (bin-packing): batch-
        tier sessions fill the FULLEST replica that still admits them,
        keeping the emptiest replicas' headroom for interactive opens —
        the placement half of "paid sessions shed last". Warmth is an
        attraction in BOTH modes: spillover subtracts the bias from the
        load (a warm replica looks emptier), packing adds it (a warm
        replica looks fuller) — negating the spillover rank wholesale
        would turn the warm bonus into a cold preference."""
        from dvf_tpu.fleet.replica import HEALTHY

        banned = set(exclude or ())
        ok = [r for r in replicas
              if r.state == HEALTHY and r.id not in banned]

        def rank(r):
            cold = 1
            if key is not None and warm:
                cold = 0 if key in set(warm.get(r.id) or ()) else 1
            bias = 1 - cold   # bounded +1 attraction for a warm pool
            if prefer_packed:
                return (-(load.get(r.id, 0) + bias), cold, r.id)
            return (load.get(r.id, 0) - bias, cold, r.id)

        return sorted(ok, key=rank)

    def record_tier_rejection(self) -> None:
        with self._lock:
            self.tier_rejections += 1

    def record_warm_placement(self) -> None:
        with self._lock:
            self.warm_placements += 1

    def record_spillover(self, n: int = 1) -> None:
        with self._lock:
            self.spillovers += n

    def record_rejection(self, tier: Optional[int] = None) -> None:
        with self._lock:
            self.rejections += 1
            if tier is not None:
                t = int(tier)
                self.rejections_by_tier[t] = (
                    self.rejections_by_tier.get(t, 0) + 1)

    def stats(self) -> dict:
        with self._lock:
            return {"spillovers": self.spillovers,
                    "rejections": self.rejections,
                    "warm_placements": self.warm_placements,
                    "tier_rejections": self.tier_rejections,
                    "rejections_by_tier": dict(self.rejections_by_tier)}
