"""Fleet admission: least-loaded placement with spillover.

The profiling-driven adaptive distributed-inference pattern (PAPERS.md,
arXiv:2605.25682) at the serving layer: new sessions open on the
least-loaded healthy replica; when that replica's own admission gate is
full (``serve``-level ``max_sessions``), the open *spills over* to the
next candidate instead of failing; only when EVERY healthy replica has
refused does the fleet reject. Load is the router's count of sessions it
has bound to each replica — a placement heuristic only; the replica's
own gate stays the source of truth, so a stale count can cost one extra
spillover hop, never a wrong admission.

Affinity is the other half of placement and is deliberately NOT here:
once a session is bound, every one of its frames goes to that replica
(per-session index monotonicity needs one reorder buffer), so placement
decisions happen only at open and at migration — both route through
:meth:`SpilloverAdmission.candidates`.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence


class SpilloverAdmission:
    """Candidate ordering + admission counters for the fleet router."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spillovers = 0   # opens that fell past their first choice
        self.rejections = 0   # opens refused by every healthy replica

    def candidates(
        self,
        replicas: Sequence,                  # ReplicaHandle, .state/.id
        load: Dict[str, int],                # router's sessions-per-replica
        exclude: Optional[Iterable[str]] = None,
    ) -> List:
        """Healthy replicas, least-loaded first (id as tiebreak so equal
        loads place deterministically). ``exclude`` drops specific ids —
        migration must not re-place a session on the replica it is
        fleeing."""
        from dvf_tpu.fleet.replica import HEALTHY

        banned = set(exclude or ())
        ok = [r for r in replicas
              if r.state == HEALTHY and r.id not in banned]
        return sorted(ok, key=lambda r: (load.get(r.id, 0), r.id))

    def record_spillover(self, n: int = 1) -> None:
        with self._lock:
            self.spillovers += n

    def record_rejection(self) -> None:
        with self._lock:
            self.rejections += 1

    def stats(self) -> dict:
        with self._lock:
            return {"spillovers": self.spillovers,
                    "rejections": self.rejections}
