"""The multi-process engine path: ONE replica spanning hosts.

`runtime.engine.Engine` is single-controller: ``compile()`` warms up
with a full-batch ``device_put`` and ``submit()`` stages the whole
batch, which only works when every mesh device is addressable from this
process. On a multi-process platform (one controller per TPU host,
joined by ``jax.distributed``) no process can do either — SNIPPETS.md
[1]/[2] name the actual contract: *pjit runs one program across all
devices of all hosts*, and each process touches only its own shards.

:class:`MultiHostEngine` is the engine for that shape, finishing the
seeds in ``parallel/mesh.py``/``parallel/distributed.py``:

- **bring-up**: ``init_distributed()`` (env-driven, no-op single-host)
  then ``global_mesh`` over ALL processes' devices, data axis outermost
  so DCN carries only batch scatter (the scaling-book layout rule);
- **per-host ingest shards**: each host stages only its own rows —
  ``jax.make_array_from_process_local_data`` binds the local slab to the
  global array, the multi-controller twin of the streamed assembler's
  per-shard ``device_put``;
- **one pjit program**: the same uint8-wire step the single-host engine
  builds (cast fused on device, uint8 both directions), jitted with the
  global batch sharding;
- **per-host egress shards**: each host materializes only its local
  output rows (`parallel.distributed.local_output_rows`) — D2H stays on
  each host's own PCIe, no cross-host gather.

A fleet replica that should span hosts runs this engine inside its
worker process with the peer hosts launched under the same coordinator;
host loss inside the replica is ``parallel.distributed`` elasticity
territory (`ElasticMeshRunner`), while whole-replica loss stays the
fleet router's drain/migrate/restart domain. Serving multiplexes
stateless filters only, and so does this engine — temporal state would
additionally need the cross-host replication discipline
``ElasticMeshRunner`` documents.

The 2-process CPU bring-up (gloo collectives) is pinned by
``tests/test_fleet_multiproc.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dvf_tpu.api.filter import Filter
from dvf_tpu.parallel.distributed import (
    global_mesh,
    init_distributed,
    local_output_rows,
)
from dvf_tpu.parallel.mesh import MeshConfig, batch_sharding
from dvf_tpu.utils.image import to_float, to_uint8


@dataclasses.dataclass
class MultiHostStats:
    batches: int = 0
    local_frames: int = 0
    compile_count: int = 0


class MultiHostEngine:
    """One filter program across every host of a jax.distributed cluster.

    Call :func:`parallel.distributed.init_distributed` (or construct
    with ``auto_init=True``) before building: the mesh must see every
    process's devices. All processes must construct with the same
    config and call :meth:`compile`/:meth:`submit_local` in lockstep —
    it is one SPMD program, so a missing participant is a hang (and a
    dead one surfaces as the collective errors
    ``parallel.distributed.is_peer_loss`` classifies).
    """

    def __init__(
        self,
        filt: Filter,
        config: Optional[MeshConfig] = None,
        prefer: str = "data",
        out_uint8: bool = True,
        auto_init: bool = False,
    ):
        if filt.stateful:
            raise ValueError(
                f"filter {filt.name!r} is stateful; the multi-process "
                f"serving engine runs stateless filters only (temporal "
                f"state needs the ElasticMeshRunner replication "
                f"discipline)")
        if auto_init:
            init_distributed()
        self.filter = filt
        self.out_uint8 = out_uint8
        self.mesh = global_mesh(config, prefer=prefer)
        self.process_count = jax.process_count()
        self.process_index = jax.process_index()
        self.stats = MultiHostStats()
        self._step = None
        self._sharding = None
        self._signature: Optional[Tuple] = None
        self.local_batch_size: Optional[int] = None
        self.out_local_shape: Optional[Tuple[int, ...]] = None

    def _build_step(self):
        filt = self.filter
        out_uint8 = self.out_uint8

        def step(batch):
            if batch.dtype == jnp.uint8 and not filt.uint8_ok:
                x = to_float(batch, filt.compute_dtype)
            else:
                x = batch
            y, _ = filt.fn(x, None)
            if out_uint8 and y.dtype != jnp.uint8:
                y = to_uint8(y)
            return y

        return jax.jit(step, in_shardings=(self._sharding,),
                       out_shardings=self._sharding)

    def compile(self, global_batch_shape: Tuple[int, ...],
                dtype=np.uint8) -> None:
        """Trace + warm for a fixed GLOBAL (B,H,W,C) signature. Every
        host passes the same global shape; ``local_batch_size`` comes
        back as the rows THIS host contributes per submit."""
        sig = (tuple(global_batch_shape), np.dtype(dtype))
        if sig == self._signature:
            return
        self._sharding = batch_sharding(self.mesh, global_batch_shape)
        shape = tuple(global_batch_shape)
        # Rows this process feeds: the union of the batch-axis intervals
        # its devices hold under the chosen sharding (replicated batch
        # axis ⇒ every process feeds all rows; distinct devices holding
        # the same interval dedupe).
        intervals = set()
        for d, idx in self._sharding.devices_indices_map(shape).items():
            if d.process_index == self.process_index:
                sl = idx[0]
                intervals.add((sl.start or 0,
                               shape[0] if sl.stop is None else sl.stop))
        self.local_batch_size = sum(stop - start
                                    for start, stop in intervals)
        self._step = self._build_step()
        self._signature = sig
        self.stats.compile_count += 1
        # Warm the compile cache with this host's zero shard so the
        # first real batch doesn't pay the trace/compile.
        warm = self.submit_local(
            np.zeros((self.local_batch_size, *shape[1:]), dtype=dtype),
            _warm=True)
        self.out_local_shape = tuple(warm.shape)

    def submit_local(self, local_batch: np.ndarray,
                     _warm: bool = False) -> np.ndarray:
        """Contribute this host's rows of the global batch; returns this
        host's rows of the result (blocking — multi-controller serving
        overlap belongs to the caller's threads, as in the worker loop).
        """
        if not _warm:
            if self._signature is None:
                raise ValueError("compile(global_shape) first — every "
                                 "host submits its fixed local share")
            want = (self.local_batch_size, *self._signature[0][1:])
            if tuple(local_batch.shape) != want:
                raise ValueError(
                    f"local batch {tuple(local_batch.shape)} does not "
                    f"match this host's compiled local signature {want}")
        arr = jax.make_array_from_process_local_data(
            self._sharding, np.ascontiguousarray(local_batch))
        out = self._step(arr)
        rows = local_output_rows(out)
        if not _warm:
            self.stats.batches += 1
            self.stats.local_frames += local_batch.shape[0]
        return rows
