"""Fleet tier: N engine replicas behind one front door.

The scale-out layer above ``dvf_tpu/serve`` — the "millions of users"
axis. One ``FleetFrontend`` routes client sessions across N complete
replicas (each a ``ServeFrontend`` + engine, in-process on a device
slice or in its own process) with session affinity, spillover admission,
replica health tracking with drain → migrate → restart, and fleet-merged
stats. Underneath, ``MultiHostEngine`` is the multi-process engine path:
one replica spanning every host of a ``jax.distributed`` cluster, with
per-host ingest/egress shards feeding one pjit program.
"""

from dvf_tpu.fleet.admission import SpilloverAdmission
from dvf_tpu.fleet.elastic import (
    ElasticFleetPlane,
    StandbyPool,
    live_standby_handles,
)
from dvf_tpu.fleet.multihost import MultiHostReplica
from dvf_tpu.fleet.multiproc import MultiHostEngine
from dvf_tpu.fleet.replica import (
    DEAD,
    DRAINING,
    HEALTHY,
    RESTARTING,
    LocalReplica,
    ProcessReplica,
    ReplicaHandle,
    ReplicaLostError,
)
from dvf_tpu.fleet.router import FLEET_MODES, FleetConfig, FleetFrontend

__all__ = [
    "DEAD",
    "DRAINING",
    "ElasticFleetPlane",
    "FLEET_MODES",
    "FleetConfig",
    "FleetFrontend",
    "HEALTHY",
    "LocalReplica",
    "MultiHostEngine",
    "MultiHostReplica",
    "ProcessReplica",
    "RESTARTING",
    "ReplicaHandle",
    "ReplicaLostError",
    "SpilloverAdmission",
    "StandbyPool",
    "live_standby_handles",
]
