"""Replica worker process: one ServeFrontend behind a pickle RPC.

Spawned by ``fleet.replica.ProcessReplica`` as

    python -m dvf_tpu.fleet._worker --port P --replica-id rN

The child connects back to the parent's listener (no open ports of its
own), receives the wire config (filter spec + ServeConfig fields + chaos
spec — specs, not objects; see ProcessReplica), builds and starts the
frontend, and then serves RPCs single-threaded: the frontend's own
dispatch/collect threads do the concurrent work, so one request loop is
enough, and it makes replica-side op ordering trivially serial.

Platform/devices come from the environment the parent staged
(``JAX_PLATFORMS``, ``XLA_FLAGS``): they must be set before jax imports,
which is exactly what a fresh process guarantees and an in-process
replica cannot — the reason the process transport exists.
"""

from __future__ import annotations

import argparse
import os
import sys


def _serve_config(fields: dict, chaos_spec, chaos_seed: int,
                  replica_id: str):
    from dvf_tpu.serve import ServeConfig

    chaos = None
    if chaos_spec:
        from dvf_tpu.resilience import FaultPlan

        chaos = FaultPlan.parse(chaos_spec, seed=chaos_seed)
    return ServeConfig(**fields, chaos=chaos, replica_label=replica_id)


def _await_adoption(reattach, grace_s: float, replica_id: str):
    """Parent lost mid-serve: wait up to ``grace_s`` on the reattach
    listener for a restarted front door to adopt this worker
    (continuity plane, ISSUE 19). The worker keeps its frontend — and
    every open session's queued deliveries — warm for the whole grace
    window. Returns the adopted RPC socket, or None (grace unarmed /
    expired / bad handshake): the caller shuts down."""
    if reattach is None or grace_s <= 0:
        return None
    import socket

    from dvf_tpu.fleet.replica import recv_msg, send_msg

    reattach.settimeout(grace_s)
    try:
        sock, _ = reattach.accept()
    except OSError:   # timeout included: orphaned for good
        return None
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(10)
        hello = recv_msg(sock)
        if not (isinstance(hello, tuple) and len(hello) >= 2
                and hello[0] == "adopt" and hello[1] == replica_id):
            send_msg(sock, ("err", "ServeError",
                            f"adoption refused: {hello!r}"))
            sock.close()
            return None
        send_msg(sock, ("adopted", os.getpid()))
        sock.settimeout(None)
        return sock
    except Exception:  # noqa: BLE001 — a bad suitor, not a shutdown
        try:
            sock.close()
        except OSError:
            pass
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--replica-id", default="r?")
    args = ap.parse_args(argv)

    import socket

    from dvf_tpu.fleet.replica import recv_msg, send_msg

    sock = socket.create_connection((args.host, args.port), timeout=30)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    frontend = None
    reattach = None
    try:
        send_msg(sock, ("hello", os.getpid()))
        op = recv_msg(sock)
        if op[0] != "config":
            send_msg(sock, ("err", "ServeError", f"expected config, got {op[0]!r}"))
            return 2
        cfg = op[1]
        # Pin BEFORE jax/XLA initialize (the frontend import below), so
        # every thread the runtime spawns inherits the replica's core
        # budget — the fleet's per-replica resource isolation on CPU.
        if cfg.get("cpu_affinity") and hasattr(os, "sched_setaffinity"):
            os.sched_setaffinity(0, set(cfg["cpu_affinity"]))
        try:
            import numpy as np

            from dvf_tpu.ops import get_filter
            from dvf_tpu.serve import ServeFrontend

            name, kwargs = cfg["filter"]
            frontend = ServeFrontend(
                get_filter(name, **(kwargs or {})),
                _serve_config(cfg.get("serve", {}), cfg.get("chaos_spec"),
                              cfg.get("chaos_seed", 0),
                              cfg.get("replica_id", args.replica_id)),
            ).start()
            if cfg.get("precompile"):
                # AOT warm-start before taking traffic (also runs on a
                # RESPAWN — wire_config persists, so a replaced replica
                # comes back warm through the persistent cache).
                frontend.precompile(cfg["precompile"])
        except Exception as e:  # noqa: BLE001 — startup failure → parent
            send_msg(sock, ("err", type(e).__name__, str(e)))
            return 2
        # Continuity plane: with a reattach grace armed (the fleet sets
        # it when its snapshot plane is on), bind our OWN listener so a
        # restarted front door can adopt this worker instead of losing
        # every session with the old one. The port rides the ready
        # tuple's trailing extras dict (older parents only read
        # ready[0]).
        grace_s = float(cfg.get("reattach_grace_s") or 0.0)
        replica_id = cfg.get("replica_id", args.replica_id)
        extras = {}
        if grace_s > 0:
            reattach = socket.socket()
            reattach.bind((args.host, 0))
            reattach.listen(1)
            extras["reattach_port"] = reattach.getsockname()[1]
            # Parent loss must surface as EOF/RST (the kernel closes a
            # killed front door's sockets promptly), never as an idle-
            # timeout false positive that abandons a live parent.
            sock.settimeout(None)
        send_msg(sock, ("ready", os.getpid(), extras))
        submit_errors = 0

        while True:
            try:
                op = recv_msg(sock)
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                sock = _await_adoption(reattach, grace_s, replica_id)
                if sock is None:
                    break  # parent went away for good: shut down with it
                continue
            kind = op[0]
            if kind == "submit1":
                # One-way hot path: NO reply (the fleet index is parent-
                # assigned; an ack would serialize every frame on this
                # loop's GIL latency). Errors are counted and exported
                # via health/stats — the frames themselves are covered
                # by at-most-once accounting.
                _, sid, frame, ts, tag = op
                try:
                    frontend.submit(sid, frame, ts=ts, tag=tag)
                except Exception as e:  # noqa: BLE001 — freshness-first
                    submit_errors += 1
                    print(f"[fleet-worker] submit dropped: {e!r}",
                          file=sys.stderr, flush=True)
                continue
            try:
                if kind == "stop":
                    send_msg(sock, ("ok", None))
                    break
                elif kind == "open":
                    # 6-tuple since the multi-signature frontend (the
                    # trailing op_chain), 7-tuple since the control
                    # plane (trailing tier); shorter tuples from an
                    # older parent still open on the default bucket at
                    # the default tier.
                    _, sid, slo_ms, frame_shape, frame_dtype = op[:5]
                    op_chain = op[5] if len(op) > 5 else None
                    tier = op[6] if len(op) > 6 else None
                    # The dtype crosses the wire as its original
                    # SPELLING; the frontend canonicalizes (np.dtype
                    # here would read "u8" as uint64).
                    out = frontend.open_stream(
                        session_id=sid, slo_ms=slo_ms,
                        frame_shape=frame_shape,
                        frame_dtype=frame_dtype or None,
                        op_chain=op_chain, tier=tier)
                elif kind == "poll":
                    _, sid, max_items, meta_only = op
                    got = frontend.poll(sid, max_items)
                    out = ([d._replace(frame=None) for d in got]
                           if meta_only else got)
                elif kind == "close":
                    _, sid, drain = op
                    out = frontend.close(sid, drain=drain)
                elif kind == "release":
                    out = frontend.release(op[1])
                elif kind == "drain":
                    out = frontend.drain(timeout=op[1])
                elif kind == "begin_drain":
                    out = frontend.begin_drain()
                elif kind == "health":
                    import time as _time

                    # wall_time_s: the parent's clock-offset probe for
                    # per-frame lineage re-basing (ProcessReplica.health
                    # estimates offset from the RPC midpoint). load: the
                    # cheap per-replica load row the fleet monitor
                    # caches for its elastic view.
                    out = dict(frontend.health(),
                               submit_errors=submit_errors,
                               wall_time_s=_time.time(),
                               load=frontend.load_row())
                elif kind == "stats":
                    out = {"stats": frontend.stats(),
                           "latency": frontend.latency_snapshot(),
                           "signals": frontend.signals(),
                           "health": dict(frontend.health(),
                                          submit_errors=submit_errors)}
                elif kind == "audit_probe":
                    # Cross-replica divergence probe (obs.audit): the
                    # deterministic probe frame through this replica's
                    # compiled program — the digest the fleet compares.
                    out = frontend.audit_probe(op[1] if len(op) > 1
                                               else None)
                elif kind == "trace":
                    # The frontend tracer's bounded event window + epoch
                    # (plain values): the fleet's cross-process trace
                    # aggregation rides the same RPC as every other
                    # export. Capped to the most recent 20k events: the
                    # reply is pickled while the parent holds the serial
                    # channel lock, and a full 100k-event ring (tens of
                    # MB) would stall that replica's submit hot path for
                    # the whole transfer — mid-incident, when dumps fire.
                    out = frontend.tracer.snapshot(max_events=20_000)
                else:
                    raise ValueError(f"unknown replica op {kind!r}")
            except Exception as e:  # noqa: BLE001 — op errors cross the
                # wire by name; the loop itself keeps serving
                send_msg(sock, ("err", type(e).__name__, str(e)))
                continue
            send_msg(sock, ("ok", out))
    finally:
        if frontend is not None:
            try:
                frontend.stop(timeout=5.0)
            except Exception:  # noqa: BLE001 — exit-path best effort
                pass
        for s in (sock, reattach):
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
