"""The fleet front door: N engine replicas behind one serving API.

``FleetFrontend`` is the scale-out tier ABOVE ``serve.ServeFrontend``:
the same open/submit/poll/close/stats surface, but backed by N complete
replicas (each a frontend + engine, in-process on a device slice or in
its own process — `fleet.replica`). What the fleet adds over one
frontend:

**Session affinity.** A session is bound to one replica at open and every
one of its frames goes there — per-session index monotonicity needs one
reorder buffer, so affinity is correctness, not just cache-friendliness.
The fleet owns the *client-visible* index space (submit assigns fleet
indices, carried through the replica as the slot ``tag`` exactly like the
ZMQ bridge carries remote indices), so a session keeps its index space
across a replica migration.

**Spillover admission.** Opens place on the least-loaded healthy replica
and spill to the next when a replica's own gate refuses; the fleet
rejects only when every healthy replica has (`fleet.admission`).

**Replica health + supervised replacement.** A monitor thread polls
liveness and each replica's ``health()`` export (fed by the PR 4
supervisor: a frontend that exhausted a fault budget or declared its
engine unrecoverable reads ``ok: False``). A lost or unhealthy replica is
DRAINED — no new sessions, bound sessions migrate to surviving replicas
(their delivered tail is salvaged when the replica is still reachable;
frames in flight on a dead one are gone: the reference's at-most-once
semantics, now one level up) — then restarted and rejoined, bounded by
``max_restarts``. Losses are classified as ``replica`` faults,
attributed per replica (`resilience.faults`), and injectable via the
``replica`` chaos site (`resilience.chaos`).

**Fleet stats.** Per-replica exports merge into one view: weighted
latency snapshots → fleet p50/p99 (``LatencyStats.merge_snapshots``),
fault summaries → one table with ``by_replica`` attribution
(`fleet.stats`).
"""

from __future__ import annotations

import dataclasses
import itertools
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dvf_tpu.control.controllers import TIER_BATCH, TIER_NAMES
from dvf_tpu.fleet.admission import SpilloverAdmission
from dvf_tpu.fleet.replica import (
    DEAD,
    DRAINING,
    HEALTHY,
    RESTARTING,
    LocalReplica,
    ProcessReplica,
    ReplicaHandle,
    ReplicaLostError,
)
from dvf_tpu.fleet.stats import (
    merge_fault_summaries,
    merge_latency_snapshots,
    replica_row,
)
from dvf_tpu.obs.audit import DivergenceDetector
from dvf_tpu.obs.export import FlightRecorder, attach_fleet_provider
from dvf_tpu.obs import ledger as ledger_mod
from dvf_tpu.obs.ledger import ReconfigLedger
from dvf_tpu.obs.registry import MetricsRegistry, TimeSeriesRing
from dvf_tpu.obs.trace import Tracer, merge_tracer_snapshots
from dvf_tpu.resilience.continuity import (
    ContinuityStats,
    ReplayRing,
    atomic_write_json,
    check_resume_token,
    load_json,
    make_resume_token,
    new_secret,
)
from dvf_tpu.resilience.faults import FaultError, FaultKind, FaultStats
from dvf_tpu.serve import ServeConfig
from dvf_tpu.serve.session import (
    AdmissionError,
    Delivery,
    ServeError,
    SessionClosedError,
)

FLEET_MODES = ("local", "process")


@dataclasses.dataclass
class FleetConfig:
    replicas: int = 2
    mode: str = "local"           # "local": in-process frontends on
    #   device slices (one jax runtime); "process": one child process
    #   per replica (own jax runtime, own cores — the scale-out shape)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    #   per-replica frontend template (replica_label is stamped per
    #   replica; chaos stays fleet-level — see chaos/chaos_spec below)
    filter_spec: Optional[Tuple[str, dict]] = None  # (name, kwargs) for
    #   process replicas, which rebuild the filter from the registry
    #   (closures don't pickle); optional sugar for local mode too
    health_poll_s: float = 0.25   # monitor cadence (liveness + health())
    max_restarts: int = 2         # per replica, before it stays DEAD
    migrate: bool = True          # move a lost replica's sessions to
    #   survivors (False: they close; the client sees SessionClosedError)
    devices_per_replica: int = 0  # local mode: devices per engine slice
    #   (0 = even split of jax.devices() across replicas)
    replica_env: Dict[str, str] = dataclasses.field(default_factory=dict)
    #   process mode: extra env for workers
    pin_replicas_to_cores: bool = False  # process mode: pin replica i to
    #   CPU core i (round-robin over this process's affinity mask) — the
    #   CPU-backend stand-in for "each replica owns its chips": without
    #   it one replica's XLA pool spreads over every core and an N-
    #   replica fleet has nothing left to scale into (the fleet scaling
    #   bench pins; serving defaults don't)
    startup_timeout_s: float = 120.0
    rpc_timeout_s: float = 60.0
    rpc_op_timeout_s: float = 5.0   # bounded control-plane RPCs (health
    #   probe, begin_drain): the socket deadline for ops the monitor
    #   must never sit behind (previously a hardcoded constant inside
    #   ProcessReplica — promoted so a deployment with slow replicas can
    #   widen it; exported in stats()["fleet"] provenance)
    rpc_lock_timeout_s: float = 5.0  # channel-lock bound for the same
    #   ops: how long a probe/stats pull may queue behind a busy submit
    #   before degrading to "try next tick" instead of wedging
    drain_timeout_s: float = 10.0
    max_retired: int = 64         # closed sessions kept poll-able; the
    #   oldest (and its salvaged tail frames) evicted beyond this —
    #   serve's retention discipline, mirrored: a churning fleet must
    #   not pin every dead session's tail forever
    chaos: Any = None             # fleet-level FaultPlan: the "replica"
    #   site fires in the health monitor (one event per replica per
    #   tick); per-replica serve-level chaos rides chaos_spec instead so
    #   each replica owns a deterministic plan of its own
    chaos_spec: Optional[str] = None
    chaos_seed: int = 0
    telemetry_sample_s: float = 0.0  # >0: fleet-level TimeSeriesRing of
    #   RPC-free front-door signals (placements, losses, healthy count)
    #   behind the /timeseries endpoint; per-replica signal windows live
    #   in each replica's own ring (serve.telemetry_sample_s)
    flight_dir: Optional[str] = None  # fleet flight recorder: a replica
    #   loss or a replica-side watchdog trip (stalls delta in health())
    #   dumps merged per-replica traces + fleet stats here. None = off.
    flight_min_interval_s: float = 10.0
    flight_max_total_bytes: Optional[int] = 256 * 1024 * 1024  # on-disk
    #   bound across dumps (oldest evicted; None = count cap only)
    tier_guard_frac: float = 0.85  # fleet-level tier-aware admission:
    #   batch-tier (tier >= 2) opens are refused once fleet-wide bound
    #   sessions reach this fraction of total healthy capacity
    #   (healthy replicas × serve.max_sessions) — the remaining slots
    #   are headroom reserved for interactive/standard tenants. 0
    #   disables the guard. Batch-tier opens also BIN-PACK (fullest
    #   admitting replica first) so empty replicas stay empty for
    #   high-priority arrivals; replica-local admission floors (the
    #   serve control plane) additionally push refused low-tier opens
    #   to replicas with headroom via ordinary spillover.
    precompile: Optional[list] = None  # --precompile manifest entries
    #   (runtime.signature.parse_manifest input): every replica AOT-
    #   compiles these at start — and again at RESPAWN, where the
    #   persistent compilation cache turns it into deserializes — so
    #   each signature's first real admission fleet-wide is a pool hit
    autoscale: Optional[Tuple[int, int]] = None  # (min, max) replicas:
    #   arms the elasticity loop (CLI --autoscale min:max) — a
    #   FleetElasticityController over the fleet telemetry ring drives
    #   spawn_replica()/retire_replica() between these bounds. The
    #   initial replica count is ``replicas`` clamped into the bounds.
    #   None = the fleet stays at ``replicas`` unless told otherwise.
    elastic: Any = None           # control.fleet_elastic.ElasticConfig
    #   overriding the controller knobs (min/max still come from
    #   ``autoscale`` when both are set); None = defaults
    standby_warm: int = 0         # warm standby pool size: replicas
    #   pre-spawned and AOT-precompiled (fleet.elastic.StandbyPool) so
    #   a scale-out is session-rebind time, not a cold spawn. Works
    #   with or without autoscale (manual spawn_replica() takes from
    #   the pool too). 0 = no pool, spawns are cold.
    audit_interval_s: float = 0.0  # > 0: the cross-replica divergence
    #   detector (obs.audit) runs on the monitor thread at this cadence
    #   — an identical deterministic probe frame through every healthy
    #   replica warm on a shared signature, output digests compared; a
    #   diverging replica is flagged (audit events + a flight dump) and
    #   — with audit_quarantine — retired through the retire_replica
    #   seam. 0 = manual only (audit_divergence_check()).
    audit_quarantine: bool = False  # flagged divergent replicas are
    #   drained and retired (the existing scale-in machinery) instead
    #   of just flagged — a replica provably computing WRONG pixels
    #   has no business taking traffic
    state_path: Optional[str] = None  # continuity plane (ISSUE 19): the
    #   front door periodically snapshots its session registry,
    #   placement map, and each process replica's incarnation (pid +
    #   reattach port) to this file — crash-consistent (atomic tmp +
    #   rename), so a kill -9 at any instant leaves a loadable
    #   document. None = the continuity snapshot plane is off.
    snapshot_interval_s: float = 1.0  # snapshot cadence (state_path set)
    resume_state: bool = False    # start() re-adopts still-live process
    #   replicas (and their open sessions) from state_path instead of
    #   spawning cold — the recovery half of the snapshot plane. A
    #   replica whose worker died (or whose reattach grace expired)
    #   falls back to a cold start; its sessions are gone with it.
    reattach_grace_s: float = 30.0  # how long an orphaned worker waits
    #   on its reattach listener for a restarted front door before
    #   shutting itself down (armed only when state_path is set —
    #   without a snapshot nobody can ever adopt it)
    autoplan: bool = False        # auto-plan plane at the front door:
    #   apply the CACHED plan for the dominant signature (the first
    #   --precompile manifest entry — same convention as the multihost
    #   pin) to the serve template before any replica spawns, so every
    #   replica inherits the measured operating point; a cache miss
    #   falls back to the analytic plan (never a live search — a fleet
    #   start must not hold N replicas hostage to a measurement run,
    #   and an analytic guess is never cached). Also arms the
    #   PREDICTIVE elasticity controller (slope-projected scale-out)
    #   when autoscale is on. Plan/calibration cache dir rides
    #   serve.plan_cache_dir.
    multihost_hosts: int = 0      # >= 2 arms the BIGGER-replica axis:
    #   a spawn_replica(flavor="multihost") builds one replica whose
    #   worker is a MultiHostEngine process group of this many hosts
    #   (jax.distributed, one pjit program across the group's devices),
    #   pinned to the first --precompile manifest signature (the group
    #   compiles ONE program — the manifest names it). 0 = the
    #   controller's two-axis choice always picks more-replicas.


class _FleetSession:
    """Fleet-side record of one client session: its replica binding, the
    client-visible index space, and the migration bookkeeping."""

    __slots__ = ("sid", "replica_id", "replica_sid", "generation",
                 "next_index", "last_index", "slo_ms", "frame_shape",
                 "frame_dtype", "op_chain", "tier", "lock", "tail",
                 "migrations", "lost", "polled", "closed", "orphaned",
                 "load_counted", "replay")

    def __init__(self, sid: str, replica_id: str, slo_ms, frame_shape,
                 frame_dtype, op_chain=None, tier=None,
                 replay_window: int = 0):
        self.sid = sid
        self.replica_id = replica_id
        self.replica_sid = sid           # sid@gN after migrations
        self.generation = 0
        self.next_index = 0              # fleet-owned index space
        self.last_index = -1             # monotonicity watermark (poll)
        self.slo_ms = slo_ms
        self.frame_shape = frame_shape   # declared at open (may be None)
        self.frame_dtype = frame_dtype
        self.op_chain = op_chain         # declared chain — a migration
        #   re-declares it so the survivor routes to the same bucket
        self.tier = tier                 # priority tier — controller
        #   state that SURVIVES migration: re-declared at the migration
        #   open, so the survivor's control plane sheds this session in
        #   the same order the lost replica's would have
        self.lock = threading.Lock()
        self.tail: List[Delivery] = []   # salvaged pre-migration deliveries
        self.migrations = 0
        self.lost = 0                    # submits dropped on a lost replica
        self.polled = 0                  # deliveries handed to the client
        self.closed = False
        self.orphaned = False            # no replica could take it
        self.load_counted = True         # guards double-decrement
        self.replay = (ReplayRing(replay_window) if replay_window > 0
                       else None)        # delivered-tail ring, FLEET
        #   index space — lives in the fleet session record, so it
        #   survives replica migration (the replica-side ring dies with
        #   the replica) and serves resume_stream() replays


class FleetFrontend:
    """N-replica serving tier behind one front door (module docstring)."""

    def __init__(self, filt=None, config: Optional[FleetConfig] = None):
        self.config = config or FleetConfig()
        if self.config.mode not in FLEET_MODES:
            raise ValueError(
                f"mode must be one of {FLEET_MODES}, got "
                f"{self.config.mode!r}")
        if self.config.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.config.mode == "process" and self.config.filter_spec is None:
            raise ValueError(
                "process mode needs filter_spec=(name, kwargs): a filter "
                "object's closures cannot cross the process boundary")
        if filt is None:
            if self.config.filter_spec is None:
                raise ValueError("need a filter or config.filter_spec")
            from dvf_tpu.ops import get_filter

            name, kwargs = self.config.filter_spec
            filt = get_filter(name, **(kwargs or {}))
        self.filter = filt
        self.faults = FaultStats()        # fleet-observed faults (replica
        #   losses), attributed per replica via record(..., replica=)
        self.admission = SpilloverAdmission()
        self.replica_losses = 0
        self.migrated_sessions = 0
        self.orphaned_sessions = 0
        self.order_violations = 0         # should stay 0: the affinity +
        #   migration protocol guarantees per-session index monotonicity
        self.scale_outs = 0               # applied spawn_replica calls
        self.scale_ins = 0                # applied retire_replica calls
        self.standby_adoptions = 0        # scale-outs served warm (the
        #   standby pool had a pre-spawned replica ready)
        self.rollouts = 0                 # completed rolling_rollout calls
        self.rollout_swaps = 0            # replicas replaced across them
        # -- continuity plane (ISSUE 19): resume tokens + crash recovery.
        # The signing secret rides the state snapshot, so tokens issued
        # by a previous front-door incarnation still verify after a
        # --resume-state restart.
        self.continuity = ContinuityStats()
        self._token_secret = new_secret()
        self._snapshot_thread: Optional[threading.Thread] = None
        self._snapshot_stop = threading.Event()
        self._replicas: "Dict[str, ReplicaHandle]" = {}
        self._load: Dict[str, int] = {}
        self._replica_load: Dict[str, dict] = {}  # per-replica load rows
        #   (ServeFrontend.load_row via the health RPC), cached by the
        #   monitor so signals()/elastic_view() stay RPC-free
        self._retiring: set = set()       # replica ids mid-retire (the
        #   scale-in path owns their lifecycle; the loss monitor must
        #   not race a second drain/restart onto them)
        self._sessions: Dict[str, _FleetSession] = {}
        self._retired: Dict[str, _FleetSession] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()       # session/load registries
        self._open_lock = threading.Lock()  # serializes placements
        self._loss_lock = threading.Lock()  # serializes loss handling
        self._scale_lock = threading.Lock()  # serializes spawn/retire
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._started = False
        # -- telemetry plane: front-door tracer (lifecycle instants — the
        # replica lanes come from the replicas' own tracers via
        # trace_snapshots), metrics registry, signal window, flight
        # recorder, and the per-replica stall watermark the monitor uses
        # to turn a replica-side watchdog trip into a fleet-level dump.
        self.tracer = Tracer(enabled=self.config.serve.trace,
                             process_name="fleet")
        self.registry = MetricsRegistry()
        attach_fleet_provider(self.registry, self)
        # Fleet-tier reconfiguration ledger (obs.ledger): replica
        # spawn/retire/restart land here with their causes and measured
        # wall costs (the per-replica compile/resize events live in
        # each replica's OWN ledger, which rides its stats_full RPC).
        self.ledger: Optional[ReconfigLedger] = None
        if self.config.serve.ledger:
            self.ledger = ReconfigLedger(tracer=self.tracer, track=1)
        # -- audit plane, fleet detector (obs.audit): cross-replica
        # divergence — probe-digest comparison over the healthy
        # replicas, flagged replicas optionally retired through the
        # scale-in seam. Always constructed (cheap counters; the /audit
        # endpoint and the manual check work without a cadence);
        # audit_interval_s > 0 runs it from the monitor thread.
        self.divergence = DivergenceDetector(
            tracer=self.tracer, ledger=self.ledger,
            flight_cb=self._dump_async,
            quarantine_cb=lambda rid: self.retire_replica(
                rid, cause="audit",
                reason="cross-replica divergence quarantine"))
        self._last_audit_check = 0.0
        # -- elasticity plane (ISSUE 12): controller + standby pool. The
        # plane must exist before the ring so the ring's on_sample hook
        # can point at it; an armed autoscale implies the ring (the
        # controller is blind without a window), at the elastic cadence
        # unless something armed a faster one already.
        self.desired = self.config.replicas
        self.elastic = None
        elastic_cfg = None
        if self.config.autoscale is not None:
            from dvf_tpu.control.fleet_elastic import ElasticConfig
            from dvf_tpu.fleet.elastic import ElasticFleetPlane

            lo, hi = (int(self.config.autoscale[0]),
                      int(self.config.autoscale[1]))
            if not 1 <= lo <= hi:
                raise ValueError(
                    f"autoscale bounds must satisfy 1 <= min <= max, "
                    f"got {self.config.autoscale!r}")
            base = self.config.elastic or ElasticConfig()
            if self.config.autoplan and not base.predictive:
                # Feed-forward elasticity is the auto-plan plane's
                # fleet leg: project queue/occupancy growth from the
                # telemetry slope and spawn BEFORE refusals advance
                # (reactive pressure still wins whenever it fires
                # first — control.fleet_elastic).
                base = dataclasses.replace(base, predictive=True)
            elastic_cfg = dataclasses.replace(
                base, min_replicas=lo, max_replicas=hi)
            self.desired = min(max(self.config.replicas, lo), hi)
            self.elastic = ElasticFleetPlane(self, elastic_cfg)
        self.telemetry: Optional[TimeSeriesRing] = None
        sample_s = self.config.telemetry_sample_s or (
            1.0 if self.config.flight_dir else 0.0)  # serve's rule: an
        #   armed flight recorder implies the window it dumps
        if elastic_cfg is not None:
            # The controller's sample-count knobs (out_after, in_after,
            # cooldowns) assume its cadence: a slower ring (the flight
            # recorder's 1 Hz default) would silently rescale them all,
            # so the elastic interval puts a CEILING on the period. An
            # explicitly faster telemetry_sample_s stays (documented on
            # ElasticConfig.interval_s — one ring, fastest consumer
            # wins).
            sample_s = (elastic_cfg.interval_s if sample_s <= 0
                        else min(sample_s, elastic_cfg.interval_s))
        if sample_s > 0:
            self.telemetry = TimeSeriesRing(
                self.signals,
                interval_s=sample_s,
                name="dvf-fleet-telemetry",
                on_sample=(self.elastic.on_sample
                           if self.elastic is not None else None))
        self.flight: Optional[FlightRecorder] = None
        if self.config.flight_dir:
            self.flight = FlightRecorder(
                self.config.flight_dir, label="fleet",
                min_interval_s=self.config.flight_min_interval_s,
                max_total_bytes=self.config.flight_max_total_bytes,
                trace_fn=self.trace_snapshots,
                stats_fn=self.stats,
                ring=self.telemetry,
                ledger_fn=(self.ledger.document
                           if self.ledger is not None else None),
                audit_fn=self.audit_document)
        self._stalls_seen: Dict[str, int] = {}
        # Per-replica warm-signature sets (canonical renders), fed by
        # the health monitor from each replica's health() export and
        # updated optimistically at successful declared opens — what
        # makes spillover admission SIGNATURE-AWARE: a declared open
        # prefers a replica whose pool already holds the program.
        self._warm: Dict[str, List[str]] = {}
        from dvf_tpu.runtime.signature import canonical_op_chain_or_verbatim

        self._default_chain = canonical_op_chain_or_verbatim(self.filter.name)
        # Last-seen per-replica delivered_total: a transiently missing
        # export (busy channel → stats lock_timeout, replica mid-drain)
        # must not dip the fleet's delivered counter for one scrape —
        # rate() would read the dip+recovery as a reset+spike. A replica
        # RESTART still resets its share: that is the idiomatic counter
        # reset consumers already handle.
        self._delivered_seen: Dict[str, float] = {}
        # explain() freshness cache (see its docstring): one stats
        # fan-out per second however hard /explain is polled.
        self._explain_cache: dict = {
            "lineage": bool(self.config.serve.lineage), "replicas": {}}
        self._explain_cache_t = float("-inf")
        self._explain_cache_lock = threading.Lock()
        self._explain_refresh_lock = threading.Lock()
        # -- broadcast plane (ISSUE 17): fleet-level encode-once
        # fan-out. Built lazily at the first publish_stream(); pump
        # threads (one per published channel) own polling the
        # published session and tee its deliveries into the channel.
        self.broadcast: Any = None
        self._publish_pumps: Dict[str, dict] = {}
        self._pump_errors = 0
        self.relay_spawns = 0     # applied spawn_broadcast_relay calls
        self.relay_retires = 0    # applied retire_broadcast_relay calls
        # -- auto-plan plane (ISSUE 20): the front door applies a
        # cached (or analytic) plan BEFORE any replica exists, so every
        # replica — initial, respawn, standby, elastic spawn — inherits
        # the planned operating point through the serve template.
        self.applied_plan: Optional[dict] = None
        if self.config.autoplan:
            self._front_door_plan()
        for i in range(self.desired):
            rid = f"r{i}"
            self._replicas[rid] = self._make_replica(rid, i)
            self._load[rid] = 0
        self._rid_counter = itertools.count(self.desired)
        # Warm standby pool: pre-spawned AOT-warm replicas so a
        # scale-out is adoption, not a cold spawn (fleet.elastic).
        self.standby = None
        if self.config.standby_warm > 0:
            from dvf_tpu.fleet.elastic import StandbyPool

            self.standby = StandbyPool(self._spawn_standby,
                                       warm_target=self.config.standby_warm)
        # Two-axis inputs, loaded ONCE at construction (the controller
        # is deterministic — no file reads inside the decision loop):
        # the dominant signature the multihost flavor would pin to (the
        # first --precompile manifest entry) and its measured device
        # cost from the PR 11 stage profiles (--profile-dir).
        self._multihost_key = None
        self._profile_device_ms: Optional[float] = None
        if self.config.precompile:
            try:
                from dvf_tpu.runtime.signature import parse_manifest

                entries = parse_manifest(self.config.precompile)
            except (ValueError, TypeError):
                entries = []
            if entries and self.config.multihost_hosts >= 2:
                self._multihost_key = entries[0]["key"]
            if entries and self.config.serve.profile_dir:
                from dvf_tpu.obs.lineage import load_stage_profile

                device_ms = []
                for e in entries:
                    prof = load_stage_profile(
                        self.config.serve.profile_dir, e["key"].render())
                    comp = ((prof or {}).get("components_ms")
                            or {}).get("device") or {}
                    if comp.get("mean_ms") is not None:
                        device_ms.append(float(comp["mean_ms"]))
                if device_ms:
                    self._profile_device_ms = max(device_ms)

    def _front_door_plan(self) -> None:
        """Apply a cache-or-analytic plan to the serve TEMPLATE (config
        docstring: no live search at this tier, analytic guesses never
        cached). Plans the first --precompile manifest signature; with
        no manifest there is nothing to plan for and the hand-set
        template stands."""
        from dvf_tpu.control import plan_cache as _pc
        from dvf_tpu.control import planner as _planner

        entries = []
        if self.config.precompile:
            try:
                from dvf_tpu.runtime.signature import parse_manifest

                entries = parse_manifest(self.config.precompile)
            except (ValueError, TypeError):
                entries = []
        if not entries:
            return
        key = entries[0]["key"]
        signature = key.render()
        geometry = tuple(key.geometry)
        topo = _pc.topology_fingerprint()
        scfg = self.config.serve
        t0 = time.perf_counter()
        plan = _planner.plan_from_cache(scfg.plan_cache_dir, signature,
                                        geometry, topo)
        cache = "hit"
        if plan is None:
            cache = "miss"
            cal = _pc.load_calibrations(
                scfg.plan_cache_dir, topo,
                f"b{scfg.batch_size}|{signature}")
            prof = None
            if scfg.profile_dir:
                from dvf_tpu.obs.lineage import load_stage_profile

                prof = load_stage_profile(scfg.profile_dir, signature)
            grid = _planner.candidate_grid(batch_cap=scfg.batch_size)
            plan, _comp = _planner.plan_search(
                grid, None, cal=cal, cal_batch=scfg.batch_size,
                stage_profile=prof)
        # Replicas inherit by template mutation: every replica built
        # from here on compiles at the planned point. autoplan itself
        # stays OFF on replicas (_make_replica/_local_factory strip
        # it) — the front door planned; a replica re-searching under
        # live tenants would fight the plan it was handed.
        scfg.batch_size = plan.batch_size
        scfg.tick_s = plan.tick_s
        scfg.ingest_depth = plan.ingest_depth
        scfg.ingest = plan.ingest
        scfg.egress = plan.egress
        self.applied_plan = plan.to_doc()
        wall = (time.perf_counter() - t0) * 1e3
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.PLAN, cause=ledger_mod.CAUSE_AUTOPLAN,
                signature=signature, cache=cache,
                wall_ms=round(wall, 3), plan=plan.to_doc(),
                topology=topo, legs=0, grid=plan.grid)

    def _next_rid(self) -> str:
        return f"r{next(self._rid_counter)}"

    def _spawn_standby(self) -> ReplicaHandle:
        """StandbyPool's spawn hook: allocate the next replica id and
        build an UNSTARTED default-flavor handle (the pool's refill
        thread pays the start + precompile)."""
        rid = self._next_rid()
        return self._make_replica(rid, int(rid[1:]))

    # -- replica construction -------------------------------------------

    def _make_replica(self, rid: str, index: int) -> ReplicaHandle:
        if self.config.mode == "process":
            serve_fields = {
                f.name: getattr(self.config.serve, f.name)
                for f in dataclasses.fields(ServeConfig)
                if f.name not in ("chaos", "replica_label")
            }
            # The front door plans; a replica re-searching under live
            # tenants would fight it. plan_cache_dir stays — replicas
            # still seed their compile calibrations from it.
            serve_fields["autoplan"] = False
            affinity = None
            if self.config.pin_replicas_to_cores:
                import os as _os

                if hasattr(_os, "sched_getaffinity"):
                    cores = sorted(_os.sched_getaffinity(0))
                    affinity = [cores[index % len(cores)]]
            return ProcessReplica(
                rid,
                wire_config={
                    "filter": self.config.filter_spec,
                    "serve": serve_fields,
                    "chaos_spec": self.config.chaos_spec,
                    "chaos_seed": self.config.chaos_seed + index,
                    "cpu_affinity": affinity,
                    "precompile": self.config.precompile,
                    # Orphaned-worker grace: armed only when the
                    # snapshot plane is on (without a snapshot nobody
                    # can ever come back to adopt this worker).
                    "reattach_grace_s": (self.config.reattach_grace_s
                                         if self.config.state_path
                                         else 0.0),
                },
                env=self.config.replica_env,
                startup_timeout_s=self.config.startup_timeout_s,
                rpc_timeout_s=self.config.rpc_timeout_s,
                rpc_op_timeout_s=self.config.rpc_op_timeout_s,
                rpc_lock_timeout_s=self.config.rpc_lock_timeout_s,
            )
        return LocalReplica(rid, self._local_factory(rid, index))

    def _local_factory(self, rid: str, index: int):
        """Factory for one in-process replica: a frontend whose engine
        lives on this replica's slice of the local devices — N local
        replicas partition ``jax.devices()`` instead of contending for
        all of them."""
        config = self.config

        def make():
            import jax

            from dvf_tpu.parallel.mesh import auto_mesh_config, make_mesh
            from dvf_tpu.runtime.engine import Engine
            from dvf_tpu.serve import ServeFrontend

            devs = jax.devices()
            per = config.devices_per_replica or max(
                1, len(devs) // config.replicas)
            start = (index * per) % len(devs)
            chunk = devs[start:start + per] or devs[:1]
            chaos = None
            if config.chaos_spec:
                from dvf_tpu.resilience import FaultPlan

                chaos = FaultPlan.parse(config.chaos_spec,
                                        seed=config.chaos_seed + index)
            scfg = dataclasses.replace(config.serve, replica_label=rid,
                                       chaos=chaos, autoplan=False)
            engine = Engine(self.filter,
                            mesh=make_mesh(auto_mesh_config(len(chunk)),
                                           devices=chunk))
            fe = ServeFrontend(self.filter, scfg, engine=engine).start()
            if config.precompile:
                fe.precompile(config.precompile)
            return fe

        return make

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "FleetFrontend":
        if self._started:
            raise ServeError("fleet already started")
        self._started = True
        errors: List[BaseException] = []
        # Front-door crash recovery (ISSUE 19): a --resume-state start
        # loads the previous incarnation's snapshot, re-keys its token
        # secret, and re-ADOPTS every process replica whose worker is
        # still alive on its reattach listener — instead of spawning
        # cold over the top of it. Replicas the snapshot doesn't cover
        # (or whose worker died / grace expired) start cold as usual.
        state: Optional[dict] = None
        adoptable: Dict[str, dict] = {}
        if self.config.resume_state and self.config.state_path:
            state = load_json(self.config.state_path)
        if state is not None:
            secret = state.get("secret")
            if secret:
                try:
                    self._token_secret = bytes.fromhex(secret)
                except ValueError:
                    pass  # foreign snapshot: keep the fresh secret
            if self.config.mode == "process":
                from dvf_tpu.fleet.replica import pid_alive

                for rid, row in (state.get("replicas") or {}).items():
                    if (rid in self._replicas and row.get("pid")
                            and row.get("reattach_port")
                            and pid_alive(int(row["pid"]))):
                        adoptable[rid] = row

        adopted: set = set()

        def boot(r: ReplicaHandle) -> None:
            row = adoptable.get(r.id)
            if row is not None:
                try:
                    r.adopt(int(row["pid"]), int(row["reattach_port"]))
                    adopted.add(r.id)
                    self.continuity.inc("adopted_replicas")
                    return
                except Exception:  # noqa: BLE001 — the worker died (or
                    pass           # its grace expired): cold start below
            try:
                r.start()
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=boot, args=(r,),
                                    name=f"dvf-fleet-boot-{r.id}")
                   for r in self._replicas.values()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            self.stop()
            raise ServeError(f"fleet start failed: {errors[0]!r}") from errors[0]
        if state is not None:
            self._resume_sessions(state, adopted)
        if self.config.state_path:
            self._snapshot_stop.clear()
            self._snapshot_thread = threading.Thread(
                target=self._snapshot_loop, name="dvf-fleet-snapshot",
                daemon=True)
            self._snapshot_thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="dvf-fleet-health", daemon=True)
        self._monitor.start()
        if self.standby is not None:
            self.standby.start()
        if self.elastic is not None:
            self.elastic.start()
        if self.telemetry is not None:
            self.telemetry.start()
        return self

    def stop(self, timeout: float = 15.0) -> None:
        self._stop.set()
        self._wake.set()
        self._snapshot_stop.set()
        if self._snapshot_thread is not None:
            self._snapshot_thread.join(timeout=timeout)
            self._snapshot_thread = None
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.elastic is not None:
            self.elastic.stop()
        # Broadcast before the replicas: the pumps poll sessions THROUGH
        # the front door, and relays/fan-out workers must be joined
        # before the conftest guard's sweep (dvf-fleet-bcast*,
        # dvf-bcast*).
        with self._lock:
            pumps = list(self._publish_pumps.values())
            self._publish_pumps.clear()
        for p in pumps:
            p["stop"].set()
        for p in pumps:
            p["thread"].join(timeout=timeout)
        if self.broadcast is not None:
            self.broadcast.stop(timeout=timeout)
        if self._monitor is not None:
            self._monitor.join(timeout=timeout)
            self._monitor = None
        if self.standby is not None:
            # Before the serving replicas: a standby outliving the
            # fleet is a leaked child (the conftest guard's contract).
            self.standby.stop(timeout=timeout)
        with self._scale_lock:
            # Exclude an in-flight spawn/retire: spawn_replica holds
            # this lock across its stop-check + insert, so by the time
            # we snapshot, the spawn either aborted on _stop or its
            # replica is in the dict for the sweep — no worker can
            # slip in between snapshot and join and outlive shutdown.
            threads = [threading.Thread(target=r.stop, args=(timeout,),
                                        name=f"dvf-fleet-stop-{r.id}")
                       for r in list(self._replicas.values())]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)

    def __enter__(self) -> "FleetFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def crash(self) -> None:
        """Chaos/bench-only: die like ``kill -9`` on the FRONT DOOR.
        Every front-door thread stops, each process replica's RPC
        channel is dropped WITHOUT a stop op, and the child processes
        are abandoned ALIVE — exactly the wreckage a restarted
        ``FleetFrontend(resume_state=True)`` must re-adopt from the
        state snapshot. Local-mode replicas have no existence outside
        this process, so they degrade to a plain stop."""
        self._stop.set()
        self._wake.set()
        self._snapshot_stop.set()
        if self._snapshot_thread is not None:
            self._snapshot_thread.join(timeout=5.0)
            self._snapshot_thread = None
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.elastic is not None:
            self.elastic.stop()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            pumps = list(self._publish_pumps.values())
            self._publish_pumps.clear()
        for p in pumps:
            p["stop"].set()
        if self.standby is not None:
            self.standby.stop(timeout=5.0)
        for r in list(self._replicas.values()):
            if isinstance(r, ProcessReplica):
                r.abandon()
            else:
                try:
                    r.stop(timeout=2.0)
                except Exception:  # noqa: BLE001 — crash teardown
                    pass

    # -- client API -----------------------------------------------------

    def open_stream(
        self,
        session_id: Optional[str] = None,
        slo_ms: Optional[float] = None,
        frame_shape: Optional[tuple] = None,
        frame_dtype: Any = None,
        op_chain: Optional[str] = None,
        tier: Optional[int] = None,
    ) -> str:
        """Admit one stream, signature-aware: a declared
        ``(op_chain, frame_shape, frame_dtype)`` prefers a replica whose
        program pool is already WARM for that canonical key (admission
        is a pool hit, not a compile), then least-loaded; cold admits
        and undeclared opens place least-loaded-first exactly as
        before. Spills over when a replica's own gate refuses; raises
        ``AdmissionError`` only when every healthy replica has — and
        the rejection enumerates the signatures the fleet CAN serve
        cheaply."""
        key_render = self._signature_render(op_chain, frame_shape,
                                            frame_dtype)
        low_tier = tier is not None and int(tier) >= TIER_BATCH
        with self._open_lock:
            sid = (session_id if session_id is not None
                   else f"fs{next(self._ids)}")
            with self._lock:
                if sid in self._sessions or sid in self._retired:
                    raise ServeError(f"session id {sid!r} already exists")
                load = dict(self._load)
                warm = {rid: list(v) for rid, v in self._warm.items()}
            if low_tier and self.config.tier_guard_frac > 0:
                # Tier-aware capacity guard: refuse batch tier while the
                # fleet is near capacity — the remaining slots are
                # reserved headroom for higher-priority arrivals.
                # list() snapshot: the elastic apply thread inserts/pops
                # replicas concurrently (one C-level call, GIL-atomic —
                # a bare generator over .values() would raise mid-scan).
                healthy = sum(1 for r in list(self._replicas.values())
                              if r.state == HEALTHY)
                cap = healthy * self.config.serve.max_sessions
                if cap and sum(load.values()) >= \
                        self.config.tier_guard_frac * cap:
                    self.admission.record_tier_rejection()
                    self.admission.record_rejection(
                        tier=tier if tier is not None
                        else self.config.serve.default_tier)
                    raise AdmissionError(
                        f"tier {tier} not admitted: fleet at "
                        f"{sum(load.values())}/{cap} bound sessions "
                        f"(>= {self.config.tier_guard_frac:g} guard) — "
                        f"remaining capacity is reserved for "
                        f"interactive/standard tiers")
            cands = self.admission.candidates(
                list(self._replicas.values()), load,
                warm=warm, key=key_render, prefer_packed=low_tier)
            if not cands:
                self.admission.record_rejection(
                    tier=tier if tier is not None
                    else self.config.serve.default_tier)
                raise AdmissionError("no healthy replicas in the fleet")
            hops = 0
            last_refusal: Optional[AdmissionError] = None
            for r in cands:
                born = r.started_at  # incarnation marker, see below
                try:
                    r.open_stream(sid, slo_ms=slo_ms,
                                  frame_shape=frame_shape,
                                  frame_dtype=frame_dtype,
                                  op_chain=op_chain, tier=tier)
                except AdmissionError as e:
                    last_refusal = e
                    hops += 1
                    continue
                except ReplicaLostError as e:
                    self._note_loss(r, e)
                    hops += 1
                    continue
                if hops:
                    self.admission.record_spillover(hops)
                if key_render is not None:
                    if key_render in set(warm.get(r.id) or ()):
                        self.admission.record_warm_placement()
                    with self._lock:
                        # Optimistic warm update: the replica compiled
                        # (or pool-hit) this signature just now — don't
                        # wait one health-poll period to route follow-up
                        # opens of the same key here.
                        kn = self._warm.setdefault(r.id, [])
                        if key_render not in kn:
                            kn.append(key_render)
                s = _FleetSession(sid, r.id, slo_ms, frame_shape,
                                  frame_dtype, op_chain=op_chain,
                                  tier=tier,
                                  replay_window=self.config.serve
                                  .replay_window)
                with self._lock:
                    self._sessions[sid] = s
                    self._load[r.id] = self._load.get(r.id, 0) + 1
                if r.state != HEALTHY or r.started_at != born:
                    # The replica was lost (or already replaced — fresh
                    # started_at) between the replica-side open and our
                    # registration, so the monitor's session snapshot
                    # missed this one: migrate it ourselves instead of
                    # handing the client a permanently stranded sid.
                    self._migrate(s, r, reachable=False)
                return sid
            self.admission.record_rejection(
                tier=tier if tier is not None
                else self.config.serve.default_tier)
            raise AdmissionError(
                f"every healthy replica refused this stream "
                f"({len(cands)} tried; last refusal: {last_refusal}); "
                f"warm signatures across the fleet: "
                f"{self._fleet_warm_signatures()}")

    def _signature_render(self, op_chain, frame_shape, frame_dtype
                          ) -> Optional[str]:
        """Canonical render of a declared signature (the warm-set match
        key); None when undeclared or unparseable (placement falls back
        to pure least-loaded — never a refusal from here)."""
        if frame_shape is None:
            return None
        try:
            from dvf_tpu.runtime.signature import make_key

            return make_key(
                op_chain if op_chain is not None else self._default_chain,
                frame_shape, frame_dtype).render()
        except (ValueError, TypeError):
            return None

    def _fleet_warm_signatures(self) -> List[str]:
        with self._lock:
            out = set()
            for keys in self._warm.values():
                out.update(keys)
        return sorted(out)

    def submit(self, session_id: str, frame: np.ndarray,
               ts: Optional[float] = None, tag: Any = None) -> int:
        """Enqueue one frame; returns its FLEET index — the session's
        client-visible index space, owned here so it survives replica
        migration. A frame submitted while the session's replica is lost
        (pre-migration window) is dropped and counted (``lost``):
        freshness-first at-most-once, the same contract as every other
        drop bound in the system."""
        s = self._session(session_id)
        with s.lock:
            if s.closed or s.orphaned:
                raise SessionClosedError(
                    f"session {session_id!r} is closed"
                    + (" (orphaned by replica loss)" if s.orphaned else ""))
            idx = s.next_index
            s.next_index += 1
            if s.frame_shape is None:
                # Learn the geometry from the first frame: a later
                # migration re-declares it, so a survivor pinned to a
                # different signature refuses at the migration open
                # (clean orphan) instead of silently eating mismatched
                # frames forever.
                s.frame_shape = tuple(frame.shape)
                s.frame_dtype = frame.dtype
            r = self._replicas.get(s.replica_id)
            if r is None:
                # Binding raced a replica removal (scale-in edge): the
                # frame is dropped at-most-once; the next submit sees
                # the migrated binding.
                s.lost += 1
                return idx
            try:
                r.submit(s.replica_sid, frame, ts=ts, tag=(idx, tag))
            except ReplicaLostError as e:
                s.lost += 1
                self._note_loss(r, e)
            except (SessionClosedError, KeyError):
                # Replica-side close/forget raced a migration or replica
                # replacement; the frame is gone but the session lives
                # on its (re)bound replica.
                s.lost += 1
        return idx

    def poll(self, session_id: str,
             max_items: Optional[int] = None,
             meta_only: bool = False) -> list:
        """Pop completed deliveries (fleet index space). Salvaged
        pre-migration tail first, then the live replica. ``meta_only``
        drops the frame payloads — the fleet bench's counting mode, so
        measuring N replicas doesn't serialize N replicas' pixels
        through the front door."""
        s = self._session(session_id)
        # Continuity chaos sites model the CLIENT-facing wire, so they
        # wrap the fleet's bookkeeping: a net_partition costs this poll
        # its delivery opportunity (frames stay queued replica-side —
        # delay, never loss), while net_dup/net_reorder below mutate
        # only what the client sees (the replay ring and the
        # monotonicity watermark saw the clean stream).
        chaos = self.config.chaos
        if chaos is not None:
            try:
                chaos.fire("net_partition")
            except FaultError as e:
                self.continuity.inc("partitions")
                self.faults.record(FaultKind.PARTITION, e)
                if self.ledger is not None:
                    self.ledger.record(
                        ledger_mod.PARTITION,
                        cause=ledger_mod.CAUSE_RECOVERY,
                        sid=session_id, plane="fleet")
                return []
            try:
                chaos.fire("net_delay")   # delay_s rules sleep in fire()
            except FaultError:
                pass  # a raising net_delay rule degrades to a no-op —
                #   the site's contract is latency, not loss
        out: List[Delivery] = []
        with s.lock:
            if s.tail:
                take = (len(s.tail) if max_items is None
                        else min(max_items, len(s.tail)))
                out.extend(s.tail[:take])
                del s.tail[:take]
            want = None if max_items is None else max_items - len(out)
            if want is None or want > 0:
                if not s.orphaned:
                    # .get: a retired session may outlive its replica
                    # (scale-in removed it) — its salvaged tail above is
                    # all there is.
                    r = self._replicas.get(s.replica_id)
                    got = []
                    if r is not None:
                        try:
                            got = r.poll(s.replica_sid, want,
                                         meta_only=meta_only)
                        except (ReplicaLostError, KeyError) as e:
                            if isinstance(e, ReplicaLostError):
                                self._note_loss(r, e)
                            got = []
                    out.extend(self._map_deliveries(s, got, replica=r))
            if s.replay is not None:
                for d in out:
                    s.replay.push(d.index, d)
            for d in out:
                if d.index <= s.last_index:
                    self.order_violations += 1
                else:
                    s.last_index = d.index
            s.polled += len(out)
        if chaos is not None and out:
            out = chaos.dup("net_dup", out)
            out = chaos.reorder("net_reorder", out)
        return out

    def _map_deliveries(self, s: _FleetSession, got: list,
                        replica: Optional[ReplicaHandle] = None) -> list:
        """Replica deliveries → fleet deliveries: the fleet index rides
        the slot tag (ZMQ-bridge style); the user's tag comes back out.

        Frame lineage crossing the hop is RE-BASED onto the front
        door's clock (the replica's marks are wall-clock stamps on ITS
        clock; ``clock_offset_s`` is the health-RPC midpoint estimate —
        0 for in-process replicas) and then extended with the ``rpc``
        component: replica delivery → this poll's pickup, so the
        telescoping additivity (components sum to end-to-end latency)
        survives a ProcessReplica boundary."""
        offset = (replica.clock_offset_s if replica is not None else 0.0)
        now = None
        mapped = []
        for d in got:
            if isinstance(d.tag, tuple) and len(d.tag) == 2:
                fleet_idx, user_tag = d.tag
            else:  # untagged (shouldn't happen): fall back to replica idx
                fleet_idx, user_tag = d.index, d.tag
            lin = d.lineage
            if lin is not None:
                if offset:
                    lin.rebase(-offset)
                if now is None:
                    now = time.time()
                lin.mark("rpc", now)
            mapped.append(d._replace(index=fleet_idx, tag=user_tag))
        return mapped

    def close(self, session_id: str, drain: bool = True) -> None:
        s = self._session(session_id)
        with s.lock:
            s.closed = True
            self._uncount_load(s)
            if not s.orphaned:
                r = self._replicas.get(s.replica_id)
                if r is not None:
                    try:
                        r.close(s.replica_sid, drain=drain)
                    except (ReplicaLostError, KeyError) as e:
                        if isinstance(e, ReplicaLostError):
                            self._note_loss(r, e)
        self._retire(session_id, s)

    def _retire(self, session_id: str, s: _FleetSession) -> None:
        """Move a closed session to the bounded retired map (still
        poll-able for its tail until evicted or released)."""
        with self._lock:
            if self._sessions.pop(session_id, None) is not None:
                self._retired[session_id] = s
                while len(self._retired) > self.config.max_retired:
                    self._retired.pop(next(iter(self._retired)))

    def release(self, session_id: str) -> None:
        """Forget a session: drop its binding and its replica-side
        retained tail."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
            if s is None:
                s = self._retired.pop(session_id, None)
        if s is None:
            return
        with s.lock:
            if not s.closed:
                raise ServeError(
                    f"session {session_id!r} is still open; close() first")
            s.tail.clear()
            if not s.orphaned:
                r = self._replicas.get(s.replica_id)
                if r is not None:
                    try:
                        r.release(s.replica_sid)
                    except (ReplicaLostError, KeyError, ServeError):
                        pass

    # -- continuity plane: resume tokens + delivered-tail replay ---------

    def resume_token(self, session_id: str) -> str:
        """Opaque resume credential for one session. The epoch is the
        session's migration generation at issue time (informational —
        verification keys on the MAC, so a token issued before a
        migration still resumes the session after it). Because the
        signing secret rides the state snapshot, tokens also survive a
        front-door crash + ``resume_state`` restart."""
        s = self._session(session_id)
        return make_resume_token(session_id, s.generation,
                                 self._token_secret)

    def resume_stream(self, session_id: str, token: str,
                      from_index: int = 0) -> list:
        """Replay the session's delivered tail from ``from_index``
        (fleet index space). A reconnecting client hands back its token
        plus the first index it has NOT seen; everything retained in
        the replay window comes back in index order — the client dedups
        by index, which upgrades at-most-once to effectively-exactly-
        once within the window. Raises ``ServeError`` on a bad token
        (wrong session, wrong incarnation without a snapshot, forged)."""
        s = self._session(session_id)
        epoch = check_resume_token(token, session_id, self._token_secret)
        if epoch is None:
            self.continuity.inc("resume_rejected")
            raise ServeError(
                f"resume rejected for session {session_id!r}: token "
                f"did not verify")
        replayed = ([] if s.replay is None
                    else [d for _, d in s.replay.replay_from(from_index)])
        self.continuity.inc("resumes")
        self.continuity.inc("replays")
        self.continuity.inc("replayed_frames", len(replayed))
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.RESUME, cause=ledger_mod.CAUSE_RECOVERY,
                sid=session_id, epoch=epoch, from_index=from_index,
                replayed=len(replayed))
        return replayed

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values() if not s.closed)

    def _session(self, session_id: str) -> _FleetSession:
        with self._lock:
            s = (self._sessions.get(session_id)
                 or self._retired.get(session_id))
        if s is None:
            raise KeyError(f"unknown session {session_id!r}")
        return s

    def _uncount_load(self, s: _FleetSession) -> None:
        """Placement-load decrement, exactly once per session."""
        if s.load_counted:
            s.load_counted = False
            with self._lock:
                if self._load.get(s.replica_id, 0) > 0:
                    self._load[s.replica_id] -= 1

    # -- replica health + replacement -----------------------------------

    def _note_loss(self, r: ReplicaHandle, exc: BaseException) -> None:
        """Any thread observed a replica failure: wake the monitor,
        which owns the drain/migrate/restart procedure (and records the
        loss exactly once — a thousand failed submits against one dead
        replica is ONE replica fault, not a thousand)."""
        del exc
        self._wake.set()

    def _monitor_loop(self) -> None:
        chaos = self.config.chaos
        while not self._stop.is_set():
            self._wake.wait(self.config.health_poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            for r in list(self._replicas.values()):
                if self._stop.is_set():
                    return
                if r.state in (RESTARTING, DEAD) or r.id in self._retiring:
                    continue  # a mid-retire replica's lifecycle belongs
                    #   to retire_replica (its death there is at-most-
                    #   once salvage, not a loss to re-handle)
                if chaos is not None:
                    try:
                        chaos.fire("replica")
                    except Exception as e:  # noqa: BLE001 — ChaosFault
                        # Injected replica loss: make it REAL (a process
                        # replica dies for good) so recovery is exercised
                        # against an actually-unreachable peer.
                        r.kill()
                        self._handle_loss(r, e)
                        continue
                if not r.alive():
                    self._handle_loss(r, ReplicaLostError(
                        f"replica {r.id}: process/frontend died"))
                    continue
                try:
                    h = r.health()
                except ReplicaLostError as e:
                    self._handle_loss(r, e)
                    continue
                except Exception:  # noqa: BLE001 — transient RPC noise:
                    continue       # liveness will catch a real death
                if not h.get("ok", False):
                    self._handle_loss(
                        r, ServeError(f"replica {r.id} unhealthy: "
                                      f"{h.get('error')}"),
                        reachable=True)
                    continue
                # Replica-side truth about warm signatures (its program
                # pool + live buckets) refreshes the fleet's placement
                # map — the optimistic per-open updates converge to this.
                warm = h.get("warm_signatures")
                if warm is not None:
                    with self._lock:
                        self._warm[r.id] = list(warm)
                # Cache the replica's cheap load row: what keeps the
                # fleet signals()/elastic_view() RPC-free — the
                # elasticity controller reads THIS, one health poll old.
                load_row = h.get("load")
                if isinstance(load_row, dict):
                    with self._lock:
                        self._replica_load[r.id] = load_row
                # Replica-side watchdog trips surface in the health
                # export's stalls counter; a rising watermark is the
                # fleet-level flight trigger — the replica recovered on
                # its own (PR-4 supervision), but "p99 was blown at
                # 14:02" now has a merged-trace artifact.
                stalls = int(h.get("stalls") or 0)
                if stalls > self._stalls_seen.get(r.id, 0):
                    self._stalls_seen[r.id] = stalls
                    self.tracer.instant("replica_stall", track=0,
                                        replica=r.id, stalls=stalls)
                    self._dump_async(f"replica {r.id} watchdog stall "
                                     f"(stalls={stalls})")
            # Cross-replica divergence cadence (obs.audit): one probe
            # fan-out per audit_interval_s from this thread — the same
            # bounded per-replica RPC discipline as the health poll
            # (busy channel → that replica is unprobeable this round).
            if self.config.audit_interval_s > 0:
                now = time.monotonic()
                if now - self._last_audit_check \
                        >= self.config.audit_interval_s:
                    self._last_audit_check = now
                    try:
                        self.audit_divergence_check()
                    except Exception:  # noqa: BLE001 — the auditor
                        pass           # never takes down supervision

    def _handle_loss(self, r: ReplicaHandle, exc: BaseException,
                     reachable: bool = False) -> None:
        """The supervised replacement procedure (monitor thread; also
        safe from stop paths): drain (no new sessions — state flips out
        of HEALTHY, so admission skips it), migrate or close its
        sessions, then restart and rejoin within the restart budget."""
        with self._loss_lock:
            if r.id in self._retiring:
                return  # scale-in owns this replica's teardown
            if r.state not in (HEALTHY, DRAINING):
                return  # already handled (or permanently dead)
            r.state = DRAINING
            self.replica_losses += 1
            with self._lock:
                self._warm.pop(r.id, None)  # its pool is gone with it
            self.faults.record(FaultKind.REPLICA, exc, replica=r.id)
            self.tracer.instant("replica_lost", track=0, replica=r.id,
                                error=repr(exc))
            self._dump_async(f"replica {r.id} lost: {exc!r}")
            bound = [s for s in self._snapshot_sessions()
                     if s.replica_id == r.id and not s.orphaned]
            for s in bound:
                self._migrate(s, r, reachable=reachable)
            if reachable:
                # Live-but-broken (tripped budget / unrecoverable
                # engine): tear the old frontend down before respawning.
                try:
                    r.stop(timeout=2.0)
                except Exception:  # noqa: BLE001 — already broken
                    pass
            if r.restarts < self.config.max_restarts:
                r.state = RESTARTING
                t_restart = time.time()
                last: Optional[BaseException] = None
                for _ in range(2):  # one retry: a respawn that failed
                    # transiently (loaded host, slow accept) gets a
                    # second chance before the replica is written off
                    try:
                        r.restart()  # start() flips state to HEALTHY
                        with self._lock:
                            self._load[r.id] = 0
                        # Fresh frontend, fresh counters: both
                        # watermarks must reset with it — or the first
                        # post-restart watchdog trips go unnoticed and
                        # the delivered floor pins the dead counter's
                        # high-water mark forever (an idiomatic counter
                        # reset, which consumers handle).
                        self._stalls_seen.pop(r.id, None)
                        with self._lock:
                            self._delivered_seen.pop(r.id, None)
                            self._replica_load.pop(r.id, None)
                            # Fresh frontend, empty pool: nothing is
                            # warm there until health says otherwise.
                            self._warm.pop(r.id, None)
                        last = None
                        if self.ledger is not None:
                            self.ledger.record(
                                ledger_mod.REPLICA_RESTART,
                                cause=ledger_mod.CAUSE_RECOVERY,
                                replica=r.id,
                                migrated_sessions=len(bound),
                                wall_ms=(time.time() - t_restart) * 1e3,
                                reason=repr(exc), t0=t_restart)
                        break
                    except Exception as e:  # noqa: BLE001 — judged below
                        last = e
                        time.sleep(0.5)
                if last is not None:
                    r.state = DEAD
                    self.faults.record(FaultKind.REPLICA, last,
                                       replica=r.id)
                    print(f"[fleet] replica {r.id} restart failed "
                          f"(now dead): {last!r}",
                          file=sys.stderr, flush=True)
            else:
                r.state = DEAD

    def _dump_async(self, reason: str) -> None:
        """Flight dump OFF the monitor thread (FlightRecorder.
        trigger_async): the dump pulls per-replica stats/trace RPCs, and
        both trigger paths run in the thread that owns loss detection /
        migration / restart — supervision must never wait behind a dump
        mid-incident."""
        if self.flight is not None:
            self.flight.trigger_async(reason)

    def _snapshot_sessions(self) -> List[_FleetSession]:
        with self._lock:
            return list(self._sessions.values())

    # -- continuity plane: crash-consistent state snapshots --------------

    def snapshot_now(self) -> Optional[str]:
        """Write one crash-consistent continuity snapshot (atomic tmp +
        rename — either the old document or the new one is on disk, at
        every instant): the session registry, the placement map, each
        process replica's incarnation (pid + reattach port), and the
        token secret. Everything a restarted front door needs to
        re-adopt still-live replicas and their sessions without killing
        them. Returns the path, or None when the plane is unarmed."""
        path = self.config.state_path
        if not path:
            return None
        sessions = {}
        for s in self._snapshot_sessions():
            with s.lock:
                sessions[s.sid] = {
                    "replica_id": s.replica_id,
                    "replica_sid": s.replica_sid,
                    "generation": s.generation,
                    "next_index": s.next_index,
                    "last_index": s.last_index,
                    "slo_ms": s.slo_ms,
                    "frame_shape": (list(s.frame_shape)
                                    if s.frame_shape is not None
                                    else None),
                    "frame_dtype": (str(s.frame_dtype)
                                    if s.frame_dtype is not None
                                    else None),
                    "op_chain": s.op_chain,
                    "tier": s.tier,
                    "migrations": s.migrations,
                    "closed": s.closed,
                    "orphaned": s.orphaned,
                }
        replicas = {}
        for rid, r in list(self._replicas.items()):
            replicas[rid] = {
                "state": r.state,
                "pid": getattr(r, "pid", None),
                "reattach_port": getattr(r, "reattach_port", None),
                "restarts": r.restarts,
            }
        atomic_write_json(path, {
            "version": 1,
            "secret": self._token_secret.hex(),
            "mode": self.config.mode,
            "wall_time_s": time.time(),
            "sessions": sessions,
            "replicas": replicas,
        })
        self.continuity.inc("snapshots")
        return path

    def _snapshot_loop(self) -> None:
        interval = max(0.05, self.config.snapshot_interval_s)
        while not self._snapshot_stop.wait(interval):
            if self._stop.is_set():
                return
            try:
                self.snapshot_now()
            except Exception:  # noqa: BLE001 — the snapshot plane must
                pass           # never take down serving

    def _resume_sessions(self, state: dict, adopted: set) -> None:
        """Rebuild the fleet-side session registry from the previous
        incarnation's snapshot. Only sessions bound to a replica we
        actually RE-ADOPTED come back: their replica-side halves (the
        worker's own sessions, queued deliveries included) survived the
        front-door death, so open frames keep flowing under the same
        fleet indices. A session on a cold-started replica died with
        its worker — nothing to resume."""
        t0 = time.time()
        for sid, row in (state.get("sessions") or {}).items():
            if row.get("closed") or row.get("orphaned"):
                continue
            rid = row.get("replica_id")
            if rid not in adopted:
                continue
            shape = row.get("frame_shape")
            s = _FleetSession(
                sid, rid, row.get("slo_ms"),
                tuple(shape) if shape is not None else None,
                row.get("frame_dtype"), op_chain=row.get("op_chain"),
                tier=row.get("tier"),
                replay_window=self.config.serve.replay_window)
            s.replica_sid = row.get("replica_sid") or sid
            s.generation = int(row.get("generation") or 0)
            # The snapshot may lag real submits by one interval: a too-
            # low next_index re-assigns indices already in flight, which
            # the client-side dedup-by-index absorbs (the filter is
            # deterministic, so colliding frames are identical) — delay
            # or duplication, never divergence.
            s.next_index = int(row.get("next_index") or 0)
            s.last_index = int(row.get("last_index")
                               if row.get("last_index") is not None
                               else -1)
            s.migrations = int(row.get("migrations") or 0)
            with self._lock:
                if sid in self._sessions or sid in self._retired:
                    continue
                self._sessions[sid] = s
                self._load[rid] = self._load.get(rid, 0) + 1
            self.continuity.inc("adopted_sessions")
            if self.ledger is not None:
                self.ledger.record(
                    ledger_mod.RESUME, cause=ledger_mod.CAUSE_RECOVERY,
                    sid=sid, replica=rid, from_index=s.next_index,
                    t0=t0)

    def _migrate(self, s: _FleetSession, old: ReplicaHandle,
                 reachable: bool, graceful: bool = False) -> None:
        """Move one session off a lost/draining replica. Monotonicity
        argument: the binding swaps under ``s.lock``, the same lock every
        submit/poll holds for its whole replica round-trip — so the tail
        salvage below sees everything the old replica will ever deliver
        for this session, and every frame submitted after the swap
        carries a fleet index larger than anything salvaged.

        ``graceful`` is the scale-in variant (retire_replica): the
        replica is HEALTHY and draining by choice, so the session
        closes with ``drain=True`` (queued + in-flight frames still
        serve) and the salvage POLLS UNTIL QUIET instead of one shot —
        zero frame loss on the happy path. The client's submit blocks
        on ``s.lock`` for the drain window (backpressure, not loss); a
        replica that dies mid-drain degrades to the loss path's
        at-most-once salvage (the SIGKILL-during-scale-in chaos test
        pins exactly this)."""
        with s.lock:
            if s.closed or s.orphaned or s.replica_id != old.id:
                return
            # Salvage what the old replica already completed: its router
            # delivered into the session out-queue; in-flight frames
            # beyond that are written off (at-most-once). Best-effort
            # and attempted even when liveness said dead — an in-process
            # replica whose ENGINE failed still serves its out-queues
            # (a dead process replica just raises immediately here).
            try:
                old.close(s.replica_sid, drain=graceful)
            except Exception:  # noqa: BLE001 — salvage best-effort
                pass
            if graceful:
                # Drain-to-quiet: keep polling while the retiring
                # replica serves the session's queued tail; stop after
                # a quiet window (nothing new for a few probes) or the
                # drain budget. All under s.lock — the survivor's
                # deliveries cannot interleave ahead of the tail, so
                # per-session index monotonicity is preserved by
                # construction.
                deadline = time.monotonic() + self.config.drain_timeout_s
                idle = 0
                while time.monotonic() < deadline and idle < 5:
                    try:
                        got = old.poll(s.replica_sid, None)
                    except Exception:  # noqa: BLE001 — died mid-drain:
                        break          # at-most-once from here on
                    if got:
                        s.tail.extend(self._map_deliveries(
                            s, got, replica=old))
                        idle = 0
                    else:
                        idle += 1
                        time.sleep(0.02)
            try:
                s.tail.extend(self._map_deliveries(
                    s, old.poll(s.replica_sid, None), replica=old))
            except Exception:  # noqa: BLE001
                pass
            orphan = not self.config.migrate
            if not orphan:
                with self._lock:
                    load = dict(self._load)
                    warm = {rid: list(v) for rid, v in self._warm.items()}
                for target in self.admission.candidates(
                        list(self._replicas.values()), load,
                        exclude={old.id}, warm=warm,
                        key=self._signature_render(
                            s.op_chain, s.frame_shape, s.frame_dtype)):
                    new_sid = f"{s.sid}@g{s.generation + 1}"
                    try:
                        # Controller-relevant state survives migration:
                        # the tier is re-declared, so the survivor's
                        # control plane sheds this session in the same
                        # order (its quality level re-converges from the
                        # survivor's own telemetry).
                        target.open_stream(new_sid, slo_ms=s.slo_ms,
                                           frame_shape=s.frame_shape,
                                           frame_dtype=s.frame_dtype,
                                           op_chain=s.op_chain,
                                           tier=s.tier)
                    except (AdmissionError, ReplicaLostError):
                        continue
                    self._uncount_load(s)
                    s.generation += 1
                    s.replica_id = target.id
                    s.replica_sid = new_sid
                    s.migrations += 1
                    s.load_counted = True
                    with self._lock:
                        self._load[target.id] = (
                            self._load.get(target.id, 0) + 1)
                    self.migrated_sessions += 1
                    return
                # Nobody could take it: it closes under the client.
                orphan = True
            s.orphaned = True
            s.closed = True
            self.orphaned_sessions += 1
            self._uncount_load(s)
        if orphan:
            self._retire(s.sid, s)

    # -- elasticity actuator seams (control.fleet_elastic) ----------------
    # The ElasticFleetPlane's apply thread calls these; manual callers
    # (benches, an operator REPL) get the same semantics. Spawn/retire
    # serialize on _scale_lock — elasticity is a slow loop by design and
    # two concurrent scale actions would race the registries.

    def set_desired_replicas(self, n: int) -> None:
        """Record scale INTENT (the elastic plane calls this at action
        enqueue, before the spawn/retire lands): the controller reads
        ``replicas_desired`` next sample and must see its own pending
        action instead of double-firing into the apply gap."""
        with self._lock:
            self.desired = max(1, int(n))

    def rollback_desired(self, delta: int) -> None:
        """Undo intent after a failed apply (spawn raised / retire
        refused), so the controller may re-decide on a later window."""
        with self._lock:
            self.desired = max(1, self.desired + delta)

    def spawn_replica(self, flavor: Optional[str] = None,
                      cause: str = ledger_mod.CAUSE_MANUAL,
                      reason: Optional[str] = None) -> str:
        """Scale out by one replica; returns its id. Default flavor
        takes a WARM STANDBY when the pool has one (adoption: a dict
        insert — the spawn-to-first-served-frame time the elastic bench
        measures) and cold-spawns otherwise (seconds: fork + jax init +
        precompile; this call blocks for it, which is why the elastic
        plane applies off-thread). ``flavor="multihost"`` builds the
        BIGGER-replica shape instead: a MultiHostEngine process group
        (``FleetConfig.multihost_hosts`` hosts, one pjit program) pinned
        to the first precompile-manifest signature — falls back to the
        default flavor when the multihost leg is not configured."""
        t_spawn = time.time()
        with self._scale_lock:
            if self._stop.is_set():
                raise ServeError("fleet is stopping: no scale-out")
            warm = False
            if flavor == "multihost" and self._multihost_key is not None:
                rid = self._next_rid()
                h = self._make_multihost_replica(rid)
                h.start()
            else:
                h = self.standby.take() if self.standby is not None else None
                if h is not None:
                    rid = h.id
                    warm = True
                else:
                    rid = self._next_rid()
                    h = self._make_replica(rid, int(rid[1:]))
                    h.start()
            if self._stop.is_set():
                # stop() ran while the (seconds-long cold) spawn was in
                # flight: its replica sweep snapshotted _replicas before
                # this insert, so adopting now would leak a live worker
                # past shutdown — tear it down here instead.
                try:
                    h.stop(timeout=10.0)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                raise ServeError("fleet stopped during spawn")
            with self._lock:
                self._replicas[rid] = h
                self._load.setdefault(rid, 0)
            # Seed the placement map NOW (one health probe at adoption):
            # a precompiled standby is warm for the manifest signatures,
            # and the very next open should route onto the fresh replica
            # instead of waiting a health-poll period to learn that.
            try:
                warm_sigs = (h.health() or {}).get("warm_signatures")
                if warm_sigs:
                    with self._lock:
                        self._warm[rid] = list(warm_sigs)
            except Exception:  # noqa: BLE001 — the monitor converges it
                pass
            self.scale_outs += 1
            if warm:
                self.standby_adoptions += 1
            with self._lock:
                self.desired = max(self.desired, self._live_count_locked())
            self.tracer.instant("scale_out", track=0, replica=rid,
                                warm=warm, flavor=flavor or "default")
            if self.ledger is not None:
                self.ledger.record(
                    ledger_mod.REPLICA_SPAWN, cause=cause,
                    replica=rid, warm=warm, flavor=flavor or "default",
                    wall_ms=(time.time() - t_spawn) * 1e3,
                    cache="hit" if warm else "miss", reason=reason,
                    t0=t_spawn)
            self._wake.set()  # monitor: learn its warm signatures now
            return rid

    def _live_count_locked(self) -> int:
        return sum(1 for r in self._replicas.values() if r.state != DEAD)

    def _make_multihost_replica(self, rid: str):
        from dvf_tpu.fleet.multihost import MultiHostReplica

        key = self._multihost_key
        if key is None:
            raise ServeError(
                "multihost flavor needs multihost_hosts >= 2 and a "
                "--precompile manifest naming the signature the group "
                "compiles")
        return MultiHostReplica(
            rid,
            op_chain=key.op_chain,
            frame_shape=tuple(key.geometry),
            frame_dtype=str(key.np_dtype),
            hosts=self.config.multihost_hosts,
            batch_size=self.config.serve.batch_size,
            slo_ms=self.config.serve.slo_ms,
            queue_size=self.config.serve.queue_size,
            out_queue_size=self.config.serve.out_queue_size,
            startup_timeout_s=self.config.startup_timeout_s,
            rpc_timeout_s=self.config.rpc_timeout_s,
        )

    def retire_replica(self, rid: str,
                       cause: str = ledger_mod.CAUSE_MANUAL,
                       reason: Optional[str] = None) -> bool:
        """Scale in by draining one replica: admission off (state flips
        to DRAINING + replica-side ``begin_drain``), every bound session
        gracefully migrated to a survivor (drain-to-quiet salvage, then
        rebind — affinity and the fleet index space survive, exactly
        the loss path's machinery minus the loss), then terminate and
        forget the replica. False = no such healthy replica (it died,
        retired, or was never there — the controller re-decides on a
        later window)."""
        t_retire = time.time()
        with self._scale_lock:
            with self._loss_lock:
                r = self._replicas.get(rid)
                if r is None or r.state != HEALTHY:
                    return False
                self._retiring.add(rid)
                r.state = DRAINING
            try:
                try:
                    r.begin_drain()
                except Exception:  # noqa: BLE001 — a dead/busy replica
                    pass           # drains via migration regardless
                with self._open_lock:
                    # Placement barrier: an open holds this lock from
                    # candidate pick through fleet-side registration,
                    # so once we pass it, every open that chose this
                    # (then-HEALTHY) replica is registered and lands in
                    # the snapshot below; later opens see DRAINING and
                    # place elsewhere. (The post-registration
                    # incarnation check in open_stream covers the same
                    # window for the LOSS path — this makes the retire
                    # argument local.)
                    pass
                bound = [s for s in self._snapshot_sessions()
                         if s.replica_id == rid and not s.orphaned]
                for s in bound:
                    self._migrate(s, r, reachable=True, graceful=True)
                try:
                    r.stop(timeout=self.config.drain_timeout_s)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                with self._lock:
                    self._replicas.pop(rid, None)
                    self._load.pop(rid, None)
                    self._warm.pop(rid, None)
                    self._delivered_seen.pop(rid, None)
                    self._replica_load.pop(rid, None)
                self._stalls_seen.pop(rid, None)
                self.scale_ins += 1
                with self._lock:
                    self.desired = min(self.desired,
                                       max(1, self._live_count_locked()))
                self.tracer.instant("scale_in", track=0, replica=rid,
                                    migrated=len(bound))
                if self.ledger is not None:
                    self.ledger.record(
                        ledger_mod.REPLICA_RETIRE, cause=cause,
                        replica=rid, migrated_sessions=len(bound),
                        wall_ms=(time.time() - t_retire) * 1e3,
                        reason=reason, t0=t_retire)
                return True
            finally:
                self._retiring.discard(rid)

    def rolling_rollout(self, flavor: Optional[str] = None,
                        reason: Optional[str] = None) -> dict:
        """Zero-downtime config/version rollout: replace every live
        replica one at a time, spawn-before-retire, behind the warm
        standby pool (ISSUE 18).

        Per replica the sequence is the serve tier's hot swap lifted a
        level: ``spawn_replica`` brings a successor up (adopting a warm
        standby when one is ready — the fleet-scale analogue of
        compiling aside) while the incumbent keeps serving; only once
        the successor is HEALTHY does ``retire_replica`` drain the
        incumbent, migrating its bound sessions gracefully. Capacity
        never dips below N, so sessions observe a migration (already a
        no-stall path) rather than an outage.

        A replica that fails to spawn a successor aborts the rollout
        for the REMAINING incumbents (the fleet never trades a known-
        good replica for nothing); a retire that returns False (the
        incumbent died or started draining mid-rollout) is skipped —
        the loss path owns it. Both outcomes land in the summary
        ``swap`` ledger event, cause ``rollout``."""
        t0 = time.time()
        with self._lock:
            targets = [rid for rid, r in sorted(self._replicas.items())
                       if r.state == HEALTHY]
        swapped: List[dict] = []
        aborted: Optional[str] = None
        for rid in targets:
            try:
                new_rid = self.spawn_replica(
                    flavor=flavor, cause=ledger_mod.CAUSE_ROLLOUT,
                    reason=reason)
            except Exception as e:  # noqa: BLE001 — spawn failed: keep
                aborted = f"spawn failed at {rid}: {e!r}"  # the incumbent
                break
            retired = self.retire_replica(
                rid, cause=ledger_mod.CAUSE_ROLLOUT, reason=reason)
            swapped.append({"old": rid, "new": new_rid,
                            "retired": retired})
            self.rollout_swaps += 1
        self.rollouts += 1
        record = {
            "targets": len(targets),
            "swapped": [s for s in swapped if s["retired"]],
            "skipped": [s for s in swapped if not s["retired"]],
            "aborted": aborted,
            "wall_ms": round((time.time() - t0) * 1e3, 3),
        }
        self.tracer.instant("rolling_rollout", track=0,
                            targets=len(targets),
                            swapped=len(record["swapped"]),
                            aborted=aborted)
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.SWAP, cause=ledger_mod.CAUSE_ROLLOUT,
                targets=len(targets), swapped=len(record["swapped"]),
                skipped=len(record["skipped"]), flavor=flavor,
                aborted=True if aborted else None,
                wall_ms=record["wall_ms"], reason=reason or aborted,
                t0=t0)
        return record

    def flight_trip(self, reason: str) -> None:
        """Elastic-plane observability tap (scale saturation: pressure
        with every replica spawned): same off-thread fleet flight dump
        as the loss/stall paths."""
        self.tracer.instant("scale_saturated", track=0, reason=reason)
        self._dump_async(reason)

    # -- broadcast plane: publish / subscribe / relay (ISSUE 17) ---------

    def _ensure_broadcast(self):
        with self._lock:
            if self.broadcast is None:
                from dvf_tpu.broadcast import BroadcastPlane

                sc = self.config.serve
                self.broadcast = BroadcastPlane(
                    audit_wire=sc.broadcast_audit_wire,
                    chaos=self.config.chaos,
                    ingest_depth=sc.broadcast_ingest_depth,
                    sub_queue=sc.broadcast_sub_queue,
                    evict_after=sc.broadcast_evict_after,
                    keyframe_interval=sc.broadcast_keyframe_interval)
            return self.broadcast

    def publish_stream(self, session_id: str, channel: str,
                       tiers=None, poll_interval_s: float = 0.005) -> None:
        """Register a fleet session's output as broadcast channel
        ``channel``. Unlike the serve tier (an in-process tap on the
        delivery loop), the fleet front door only sees frames when
        someone polls — so publishing hands the session's polling to a
        dedicated pump thread that drains ``poll(session_id)`` into
        the channel. The publisher stops polling this session itself;
        watchers attach with :meth:`subscribe`."""
        plane = self._ensure_broadcast()
        self._session(session_id)  # raises on unknown sid, before publish
        plane.publish(channel, publisher=session_id, tiers=tiers or ())
        tap = plane.tap(channel)
        stop_evt = threading.Event()
        t = threading.Thread(
            target=self._pump_loop,
            args=(channel, session_id, stop_evt, tap),
            name=f"dvf-fleet-bcast-{channel}", daemon=True)
        with self._lock:
            self._publish_pumps[channel] = {
                "thread": t, "stop": stop_evt, "session": session_id}
        t.start()

    def _pump_loop(self, channel: str, session_id: str,
                   stop_evt: threading.Event, tap) -> None:
        while not stop_evt.is_set() and not self._stop.is_set():
            try:
                got = self.poll(session_id)
            except Exception:  # noqa: BLE001 — session released/lost:
                # the channel stays subscribable (no new frames), the
                # pump just ends; counted for stats.
                with self._lock:
                    self._pump_errors += 1
                return
            if not got:
                stop_evt.wait(0.005)
                continue
            for d in got:
                tap(d.index, d.frame, d.capture_ts)

    def unpublish_stream(self, channel: str) -> None:
        """Stop the pump and retire the channel (subscribers detach)."""
        with self._lock:
            pump = self._publish_pumps.pop(channel, None)
        if pump is not None:
            pump["stop"].set()
            pump["thread"].join(timeout=5.0)
        if self.broadcast is not None:
            self.broadcast.unpublish(channel)

    def subscribe(self, channel: str, tier=None,
                  queue_size: Optional[int] = None, abr: bool = False):
        """Attach a watcher to a published channel (serve-tier
        semantics: tier spec string or Tier, None = ladder top or —
        with ``abr`` — its cheapest rung)."""
        return self._ensure_broadcast().subscribe(
            channel, tier=tier, queue_size=queue_size, abr=abr)

    def unsubscribe(self, sub) -> None:
        if self.broadcast is not None:
            self.broadcast.unsubscribe(sub)

    def spawn_broadcast_relay(self, channel: Optional[str] = None,
                              source_tier=None, tiers=(),
                              cause: str = "manual", reason: str = ""):
        """Spawn a relay-only egress replica (the elastic plane's
        ``relay_out`` actuator, also callable by hand). ``channel``
        None picks the channel with the most direct subscribers — the
        one whose fan-out the relay relieves."""
        plane = self._ensure_broadcast()
        if channel is None:
            rows = plane.stats()["channels"]
            if not rows:
                raise ServeError("no published channel to relay")
            channel = max(
                sorted(rows),
                key=lambda c: sum(
                    t.get("subscriber_count", 0)
                    for t in rows[c]["tiers"].values()))
        node = plane.spawn_relay(channel, source_tier=source_tier,
                                 tiers=tiers)
        with self._lock:
            self.relay_spawns += 1
        self.tracer.instant("relay_out", track=0, relay=node.id,
                            channel=channel, cause=cause, reason=reason)
        if self.ledger is not None:
            self.ledger.record(
                ledger_mod.RELAY_SPAWN, cause=cause,
                replica=node.id, channel=channel, reason=reason)
        return node

    def retire_broadcast_relay(self, relay_id: Optional[str] = None,
                               cause: str = "manual",
                               reason: str = "") -> bool:
        """Retire one relay (``relay_id`` None = the newest — LIFO, the
        scale-in mirror of spawn order). Its direct subscribers are
        evicted; the upstream channel is untouched."""
        if self.broadcast is None:
            return False
        if relay_id is None:
            stats = self.broadcast.stats()["relays"]
            if not stats:
                return False
            relay_id = sorted(stats)[-1]
        try:
            self.broadcast.retire_relay(relay_id)
        except KeyError:
            return False
        with self._lock:
            self.relay_retires += 1
        self.tracer.instant("relay_in", track=0, relay=relay_id,
                            cause=cause, reason=reason)
        if self.ledger is not None:
            self.ledger.record(ledger_mod.RELAY_RETIRE, cause=cause,
                               replica=relay_id, reason=reason)
        return True

    # -- audit plane: cross-replica divergence (obs.audit) ---------------

    def _audit_signature(self) -> Optional[str]:
        """The signature to probe: the canonical render warm on the
        MOST healthy replicas (a probe is only a comparison when at
        least two replicas can run it). None = nothing shared yet."""
        with self._lock:
            warm = {rid: set(keys) for rid, keys in self._warm.items()
                    if rid in self._replicas
                    and self._replicas[rid].state == HEALTHY}
        counts: Dict[str, int] = {}
        for keys in warm.values():
            for k in keys:
                counts[k] = counts.get(k, 0) + 1
        if not counts:
            return None
        best = max(sorted(counts), key=lambda k: counts[k])
        return best if counts[best] >= 2 else None

    def audit_divergence_check(
            self, signature: Optional[str] = None) -> dict:
        """Detector 3: run the identical deterministic probe frame
        through every healthy replica warm on ``signature`` (default:
        the most widely warm one) and compare output digests. A
        replica outvoted by the majority is flagged — and, under
        ``audit_quarantine``, drained and retired through the existing
        ``retire_replica`` seam. Returns the event record
        (``verdict``: match / mismatch / skipped)."""
        signature = signature if signature is not None \
            else self._audit_signature()
        if signature is None:
            return self.divergence.check({}, signature=None)
        with self._lock:
            replicas = [(rid, r) for rid, r in self._replicas.items()
                        if r.state == HEALTHY]
        probes: Dict[str, Optional[dict]] = {}
        for rid, r in replicas:
            try:
                probes[rid] = r.audit_probe(signature)
            except Exception:  # noqa: BLE001 — unprobeable this round
                probes[rid] = None       # (busy channel, not warm, mid-
                #   drain): counted as unreachable, never judged
        return self.divergence.check(
            probes, signature=signature,
            quarantine=self.config.audit_quarantine)

    def audit_document(self) -> dict:
        """The fleet's ``/audit`` endpoint / flight-dump audit.json:
        the divergence detector's counters + event window, plus each
        reachable replica's last-known audit counters would ride its
        own /audit — the fleet document stays RPC-free."""
        doc = self.divergence.document()
        doc["label"] = "fleet"
        doc["audit_interval_s"] = self.config.audit_interval_s
        doc["quarantine"] = self.config.audit_quarantine
        return doc

    def elastic_view(self) -> dict:
        """The structured half of a fleet control row — what the
        elastic plane composes with each flat ring sample before the
        controller's decision step. RPC-free by construction: per-
        replica queue/p99 come from the monitor's cached health-RPC
        load rows (one poll period old), never from a live fan-out on
        the sampler thread."""
        with self._lock:
            load = dict(self._load)
            cached = {rid: dict(v) for rid, v in self._replica_load.items()}
            replicas = [(rid, r.state) for rid, r in self._replicas.items()]
            desired = self.desired
        live = sum(1 for _, state in replicas if state == HEALTHY)
        rows = []
        for rid, state in replicas:
            if state != HEALTHY:
                continue
            lr = cached.get(rid) or {}
            rows.append({"rid": rid,
                         "sessions": float(load.get(rid, 0)),
                         "queue_depth": lr.get("queue_depth"),
                         "p99_ms": lr.get("p99_ms")})
        return {
            "replicas_live": float(live),
            "replicas_desired": float(desired),
            "standby_warm": (float(self.standby.warm_count)
                             if self.standby is not None else 0.0),
            "capacity_sessions": float(
                live * self.config.serve.max_sessions),
            "bound_sessions": float(sum(load.values())),
            "slo_ms": float(self.config.serve.slo_ms),
            "replica_rows": rows,
            "multihost_available": self._multihost_key is not None,
            "profile_device_ms": self._profile_device_ms,
            # Relay-axis inputs (zero rows when nothing publishes:
            # relay_pressure short-circuits and the recorded window
            # stays replayable against pre-broadcast controllers).
            **self._broadcast_view(),
        }

    def _broadcast_view(self) -> dict:
        if self.broadcast is None:
            return {"broadcast_subscribers": 0.0,
                    "broadcast_dropped_total": 0.0,
                    "relays_live": 0.0}
        sig = self.broadcast.signals()
        return {
            "broadcast_subscribers": sig.get("broadcast_subscribers", 0.0),
            "broadcast_dropped_total": sig.get(
                "broadcast_dropped_total", 0.0),
            "relays_live": sig.get("broadcast_relays", 0.0),
        }

    # -- observability ---------------------------------------------------

    def trace_snapshots(self) -> List[dict]:
        """Every reachable tracer's bounded event window: the front
        door's own plus one per replica (in-process read or the
        ``trace`` RPC) — the input to ONE merged Perfetto session. A
        dead or wedged replica costs its lane, nothing else."""
        snaps: List[dict] = []
        if len(self.tracer):
            snaps.append(self.tracer.snapshot())
        for r in list(self._replicas.values()):
            try:
                snap = r.trace_snapshot()
            except Exception:  # noqa: BLE001 — lane lost, merge lives
                continue
            if snap and snap.get("events"):
                snaps.append(snap)
        return snaps

    def export_trace(self, out_path: str) -> Optional[dict]:
        """Merge every replica's trace into one Perfetto file on one
        aligned clock (``obs.trace.merge_tracer_snapshots``)."""
        return merge_tracer_snapshots(self.trace_snapshots(), out_path)

    def explain(self) -> dict:
        """Fleet-wide latency attribution: every reachable replica's
        ``explain`` decomposition (lineage-armed replicas only — arm
        with ``ServeConfig.lineage``), keyed by replica id. One stats
        RPC per process replica; a busy or dead replica costs its row.
        Always the p99 decomposition — the per-replica rows ride the
        stats RPC, which computes at the attribution default.

        Freshness-cached (attach_fleet_provider's discipline): a stats
        RPC briefly holds each replica's serial channel lock against
        its submit hot path, so a curl loop on ``/explain`` must
        coalesce onto one fan-out per second, not multiply it. The
        fan-out runs OUTSIDE the cache lock: a busy fleet's refresh
        can take seconds (bounded channel-lock waits per replica), and
        concurrent callers must get the stale cache, not a pile-up."""
        with self._explain_cache_lock:
            if time.monotonic() - self._explain_cache_t < 1.0:
                return self._explain_cache
        if not self._explain_refresh_lock.acquire(blocking=False):
            # Another caller is mid-fan-out: serve the (possibly stale,
            # at worst empty-first-call) cache rather than queueing.
            with self._explain_cache_lock:
                return self._explain_cache
        try:
            out: dict = {"lineage": bool(self.config.serve.lineage),
                         "replicas": {}}
            for rid, r in list(self._replicas.items()):
                if r.state != HEALTHY:
                    continue
                try:
                    export = r.stats_full()
                except Exception:  # noqa: BLE001 — never throws
                    continue
                attr = ((export or {}).get("stats")
                        or {}).get("attribution")
                if attr and attr.get("explain"):
                    out["replicas"][rid] = attr["explain"]
            with self._explain_cache_lock:
                self._explain_cache = out
                self._explain_cache_t = time.monotonic()
        finally:
            self._explain_refresh_lock.release()
        return out

    def signals(self) -> dict:
        """RPC-free front-door signal row (the fleet telemetry ring's
        sample: never blocks on a replica channel). Since the elastic
        fleet this is also the controller's flat input: the
        admission-refusal counters (total AND per tier — previously
        only visible in rejection strings), the cached per-replica load
        aggregates (queue depth, worst p99, shed/SLO-miss/delivered
        sums — one health-poll period old), and the scale gauges."""
        with self._lock:
            open_sessions = sum(1 for s in self._sessions.values()
                                if not s.closed)
            cached = [dict(v) for rid, v in self._replica_load.items()
                      if rid in self._replicas]
            desired = self.desired
            # Snapshot under the lock: spawn/retire mutate _replicas
            # from the elastic apply thread.
            replicas = list(self._replicas.values())
        healthy = sum(1 for r in replicas if r.state == HEALTHY)

        def agg(key, fold):
            vals = [float(v[key]) for v in cached
                    if v.get(key) is not None]
            return fold(vals) if vals else None

        out = {
            "open_sessions": float(open_sessions),
            "healthy_replicas": float(healthy),
            "replica_losses_total": float(self.replica_losses),
            "migrated_sessions_total": float(self.migrated_sessions),
            "orphaned_sessions_total": float(self.orphaned_sessions),
            "order_violations_total": float(self.order_violations),
            "tier_rejections_total": float(
                self.admission.tier_rejections),
            "replica_restarts_total": float(sum(
                r.restarts for r in replicas)),
            # -- elastic fleet: scale gauges + the controller inputs --
            "replicas_live": float(healthy),
            "replicas_desired": float(desired),
            "standby_warm": (float(self.standby.warm_count)
                             if self.standby is not None else 0.0),
            "scale_out_total": float(self.scale_outs),
            "scale_in_total": float(self.scale_ins),
            "standby_adoptions_total": float(self.standby_adoptions),
            "rollout_swaps_total": float(self.rollout_swaps),
            "admission_refusals_total": float(self.admission.rejections),
            # Cached per-replica load aggregates (RPC-free; summed
            # counters dip on a replica restart/retire — the idiomatic
            # counter reset, and a non-advancing delta reads as calm).
            "fleet_queue_depth": agg("queue_depth", sum),
            "fleet_p99_ms": agg("p99_ms", max),
            "fleet_shed_total": agg("shed_total", sum),
            "fleet_slo_miss_total": agg("slo_miss_total", sum),
            "fleet_delivered_total": agg("delivered_total", sum),
        }
        # stats() hands back a locked snapshot — record_rejection may be
        # inserting a first-of-its-tier key on an open_stream thread.
        by_tier = self.admission.stats()["rejections_by_tier"]
        for t, n in sorted(by_tier.items()):
            name = TIER_NAMES.get(t, f"tier{t}")
            out[f"admission_refusals_{name}_total"] = float(n)
        if self.ledger is not None:
            out.update(self.ledger.signals())
        out.update(self.continuity.signals())
        out.update(self.divergence.signals())
        if self.broadcast is not None:
            out.update(self.broadcast.signals())
            out["relay_spawns_total"] = float(self.relay_spawns)
            out["relay_retires_total"] = float(self.relay_retires)
            out["broadcast_pump_errors_total"] = float(self._pump_errors)
        if self.elastic is not None:
            for k, v in self.elastic.signals().items():
                out.setdefault(k, v)   # plane extras (errors,
                #   saturations); applied-scale counters stay the
                #   fleet's own
        return out

    def stats(self) -> dict:
        """The fleet view: per-replica rows + merged latency/faults."""
        # One snapshot for the whole export: the elastic apply thread
        # inserts/pops replicas concurrently (pre-elastic this dict was
        # construction-time-fixed and bare iteration was safe).
        replica_items = list(self._replicas.items())
        exports: Dict[str, Optional[dict]] = {}
        for rid, r in replica_items:
            try:
                exports[rid] = r.stats_full() if r.state == HEALTHY else None
            except ReplicaLostError as e:
                self._note_loss(r, e)
                exports[rid] = None
            except Exception:  # noqa: BLE001 — stats must never throw
                exports[rid] = None
        with self._lock:
            sessions = {**self._retired, **self._sessions}
            load = dict(self._load)
            warm = {rid: list(keys) for rid, keys in self._warm.items()}
        replica_rows = {}
        for rid, r in replica_items:
            row = replica_row(r, exports.get(rid), load.get(rid, 0))
            d = row.get("delivered_total")
            with self._lock:
                # Max semantics make concurrent stats() calls (scrape
                # provider + off-thread dump) interleaving-safe: a stale
                # reader can never LOWER the watermark. Restarts reset
                # it explicitly in _handle_loss (fresh counter).
                prev = self._delivered_seen.get(rid)
                if d is not None and (prev is None or d > prev):
                    self._delivered_seen[rid] = d
                elif d is None:
                    # Transiently unreadable export (busy channel, mid-
                    # drain): hold the last-seen value so the summed
                    # fleet counter never dips-and-recovers (a fake
                    # rate() spike).
                    row["delivered_total"] = prev
            replica_rows[rid] = row
        session_rows = {}
        for sid, s in sessions.items():
            session_rows[sid] = {
                "replica": s.replica_id,
                "submitted": s.next_index,
                "polled": s.polled,
                "lost": s.lost,
                "migrations": s.migrations,
                "tier": s.tier,
                "state": ("orphaned" if s.orphaned
                          else "closed" if s.closed else "open"),
            }
        return {
            "replicas": replica_rows,
            "sessions": session_rows,
            "open_sessions": sum(1 for s in sessions.values()
                                 if not s.closed),
            "replica_losses": self.replica_losses,
            "migrated_sessions": self.migrated_sessions,
            "orphaned_sessions": self.orphaned_sessions,
            "order_violations": self.order_violations,
            # Per-replica warm-signature map (the placement input): what
            # each replica's pool serves without a compile.
            "warm_replicas": warm,
            # -- elastic fleet: live/desired/standby + scale counters --
            "replicas_live": sum(1 for _, r in replica_items
                                 if r.state == HEALTHY),
            "replicas_desired": self.desired,
            "standby_warm": (self.standby.warm_count
                             if self.standby is not None else 0),
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "standby_adoptions": self.standby_adoptions,
            "rollouts": self.rollouts,
            "rollout_swaps": self.rollout_swaps,
            **({"standby": self.standby.stats()}
               if self.standby is not None else {}),
            **({"elastic": self.elastic.stats()}
               if self.elastic is not None else {}),
            **({"broadcast": {
                **self.broadcast.stats(),
                "relay_spawns": self.relay_spawns,
                "relay_retires": self.relay_retires,
                "pump_errors": self._pump_errors,
                "pumps": {ch: p["session"]
                          for ch, p in self._publish_pumps.items()},
            }} if self.broadcast is not None else {}),
            **self.admission.stats(),
            "faults": merge_fault_summaries(
                self.faults.summary(),
                {rid: (e or {}).get("stats", {}).get("faults")
                 for rid, e in exports.items()}),
            "recoveries": {
                rid: (e or {}).get("stats", {}).get("recoveries", 0)
                for rid, e in exports.items()
            },
            "replica_restarts": sum(r.restarts
                                    for _, r in replica_items),
            "continuity": self.continuity.summary(),
            # Config provenance for the knobs that shape recovery
            # behavior (the continuity bench records these next to its
            # measurements, so a regression is attributable to a knob
            # change, not a mystery).
            "fleet": {
                "mode": self.config.mode,
                "replicas": self.config.replicas,
                "health_poll_s": self.config.health_poll_s,
                "startup_timeout_s": self.config.startup_timeout_s,
                "rpc_timeout_s": self.config.rpc_timeout_s,
                "rpc_op_timeout_s": self.config.rpc_op_timeout_s,
                "rpc_lock_timeout_s": self.config.rpc_lock_timeout_s,
                "drain_timeout_s": self.config.drain_timeout_s,
                "state_path": self.config.state_path,
                "snapshot_interval_s": self.config.snapshot_interval_s,
                "resume_state": self.config.resume_state,
                "reattach_grace_s": self.config.reattach_grace_s,
                "replay_window": self.config.serve.replay_window,
            },
            # Auto-plan plane: the plan the front door applied to the
            # serve template (None = hand-set defaults).
            **({"plan": self.applied_plan}
               if self.applied_plan is not None else {}),
            "aggregate": merge_latency_snapshots(
                {rid: (e or {}).get("latency")
                 for rid, e in exports.items()}),
            "audit": self.divergence.stats(),
            **({"ledger": self.ledger.summary()}
               if self.ledger is not None else {}),
            **({"chaos": self.config.chaos.summary()}
               if self.config.chaos is not None else {}),
            **({"flight": self.flight.stats()}
               if self.flight is not None else {}),
        }
