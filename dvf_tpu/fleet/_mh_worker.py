"""Multi-host replica worker: one member of a jax.distributed group.

Spawned by ``fleet.multihost.MultiHostReplica`` as

    python -m dvf_tpu.fleet._mh_worker --parent-port P --peer-port Q \\
        --coordinator 127.0.0.1:C --num-processes H --process-id i \\
        --replica-id rN

with the replica's pinned signature in the ``DVF_MH_CONFIG`` env var
(JSON: op_chain / frame_shape / frame_dtype / batch_size / slo_ms —
env, not a handshake, because every group member needs it BEFORE the
lockstep engine compile, and only the leader ever talks to the parent).

All members bring up ONE pjit program: ``jax.distributed`` init (gloo
collectives on CPU), a global ``data=H`` mesh, a shared
:class:`~dvf_tpu.fleet.multiproc.MultiHostEngine` compiled for the
global batch. Process 0 — the LEADER — additionally speaks the replica
RPC to the fleet front door (the same pickle protocol as
``fleet._worker``: open/submit1/poll/close/drain/health/stats) and owns
the group's data plane: client frames queue leader-side, a batch thread
slices each global batch into per-process row intervals (computed from
the compiled sharding's ``devices_indices_map`` — never assumed),
ships peers their shards over localhost sockets, contributes its own
via ``submit_local`` (the collective synchronizes the group), gathers
the peers' output rows, and reassembles the global result in row
order. Peers run the five-line lockstep loop at the bottom.

Serving here is deliberately lean — one signature, FIFO batching, no
per-session SLO scheduling: a multihost replica exists to make ONE
heavy program wider (the controller's bigger-replica axis), not to
re-implement the single-host frontend's multi-tenant machinery. Peer
loss mid-collective surfaces as a failed ``submit_local``
(`parallel.distributed.is_peer_loss`): the leader marks itself
unhealthy, the fleet drains and respawns the whole group — replica-
granular supervision, exactly the router's existing loss domain.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import threading
import time


def _peer_loss(exc: BaseException) -> bool:
    from dvf_tpu.parallel.distributed import is_peer_loss

    return is_peer_loss(exc)


class _MhSession:
    __slots__ = ("sid", "queue", "out", "next_index", "submitted",
                 "delivered", "closed")

    def __init__(self, sid: str, queue_size: int, out_queue_size: int):
        self.sid = sid
        self.queue: "collections.deque" = collections.deque(
            maxlen=queue_size)  # drop-oldest ingress (serve's contract)
        self.out: "collections.deque" = collections.deque(
            maxlen=out_queue_size)  # bounded like ServeConfig.
        #   out_queue_size: a slow poller drops its OLDEST deliveries
        #   (freshness-first) instead of growing leader memory per frame
        self.next_index = 0
        self.submitted = 0
        self.delivered = 0
        self.closed = False


class _Leader:
    """The group leader's serving state (RPC loop + batch thread)."""

    def __init__(self, engine, cfg: dict, peers: list, intervals: dict):
        from dvf_tpu.obs.metrics import LatencyStats
        from dvf_tpu.runtime.signature import make_key

        self.engine = engine
        self.cfg = cfg
        self.peers = peers              # [(process_id, socket)]
        self.intervals = intervals      # process_id -> [(start, stop)]
        self.key_render = make_key(
            cfg["op_chain"], tuple(cfg["frame_shape"]),
            cfg["frame_dtype"]).render()
        self.latency = LatencyStats()
        self.sessions: dict = {}
        self.lock = threading.Lock()
        self.draining = False
        self.error: str | None = None
        self.submit_errors = 0
        self.batches = 0
        self.frames = 0
        self.seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._batch_loop, name="dvf-mh-batch", daemon=True)
        self._thread.start()

    # -- client ops (RPC loop thread) -------------------------------------

    def open_stream(self, sid, slo_ms=None, frame_shape=None,
                    frame_dtype=None, op_chain=None, tier=None):
        from dvf_tpu.runtime.signature import make_key
        from dvf_tpu.serve.session import AdmissionError

        del slo_ms, tier  # lean tier: FIFO over one signature
        with self.lock:
            if self.draining:
                raise AdmissionError("multihost replica is draining")
            if self.error is not None:
                raise AdmissionError(
                    f"multihost replica failed: {self.error}")
            if frame_shape is not None or op_chain is not None:
                want = make_key(
                    op_chain if op_chain is not None
                    else self.cfg["op_chain"],
                    tuple(frame_shape) if frame_shape is not None
                    else tuple(self.cfg["frame_shape"]),
                    frame_dtype if frame_dtype is not None
                    else self.cfg["frame_dtype"]).render()
                if want != self.key_render:
                    raise AdmissionError(
                        f"multihost replica serves ONE signature "
                        f"{self.key_render}; declared {want}")
            if sid in self.sessions:
                raise AdmissionError(f"session id {sid!r} already exists")
            self.sessions[sid] = _MhSession(
                sid, int(self.cfg.get("queue_size") or 64),
                int(self.cfg.get("out_queue_size") or 1024))
        return sid

    def submit(self, sid, frame, ts=None, tag=None) -> None:
        with self.lock:
            s = self.sessions.get(sid)
            if s is None or s.closed:
                raise KeyError(f"unknown session {sid!r}")
            s.queue.append((frame, ts if ts is not None else time.time(),
                            tag))
            s.submitted += 1

    def poll(self, sid, max_items=None, meta_only=False) -> list:
        with self.lock:
            s = self.sessions.get(sid)
            if s is None:
                raise KeyError(f"unknown session {sid!r}")
            n = len(s.out) if max_items is None else min(max_items,
                                                         len(s.out))
            got = [s.out.popleft() for _ in range(n)]
        if meta_only:
            got = [d._replace(frame=None) for d in got]
        return got

    def close(self, sid, drain=True) -> None:
        with self.lock:
            s = self.sessions.get(sid)
            if s is None:
                raise KeyError(f"unknown session {sid!r}")
            s.closed = True
            if not drain:
                s.queue.clear()

    def release(self, sid) -> None:
        with self.lock:
            self.sessions.pop(sid, None)

    def audit_probe(self, signature=None) -> dict:
        """Cross-replica divergence probe (obs.audit): the deterministic
        probe frame through the GROUP's own data plane — an internal
        one-frame session, so the digest covers exactly what a tenant
        would receive from this replica (shards shipped to peers, the
        collective, global-row reassembly and all). The probe tag is
        the canonical op_chain, matching the single-host flavor's
        ``engine_probe_row`` tag, so digests compare across flavors."""
        import numpy as np

        from dvf_tpu.obs.audit import frame_digest, probe_frame
        from dvf_tpu.serve.session import ServeError

        if signature is not None and signature != self.key_render:
            raise ServeError(
                f"multihost replica serves ONE signature "
                f"{self.key_render}; asked to probe {signature!r}")
        shape = tuple(self.cfg["frame_shape"])
        dtype = np.dtype(self.cfg["frame_dtype"])
        frame = probe_frame(shape, dtype, tag=self.cfg["op_chain"])
        sid = f"__audit_probe_{self.seq}_{time.monotonic_ns()}__"
        self.open_stream(sid)
        try:
            self.submit(sid, frame)
            deadline = time.time() + 15.0
            got: list = []
            while not got and time.time() < deadline:
                got = self.poll(sid, max_items=1)
                if not got:
                    time.sleep(0.01)
            if not got:
                raise ServeError("multihost audit probe timed out "
                                 "(group data plane not serving)")
            return {"signature": self.key_render,
                    "digest": frame_digest(
                        np.ascontiguousarray(got[0].frame)).hex()}
        finally:
            try:
                self.close(sid, drain=False)
                self.release(sid)
            except Exception:  # noqa: BLE001 — probe cleanup best-effort
                pass

    def begin_drain(self) -> None:
        with self.lock:
            self.draining = True

    def drain(self, timeout: float = 30.0) -> bool:
        self.begin_drain()
        with self.lock:
            for s in self.sessions.values():
                s.closed = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                if not any(s.queue for s in self.sessions.values()):
                    return True
            if self.error is not None:
                return False
            time.sleep(0.01)
        return False

    # -- exports ----------------------------------------------------------

    def health(self) -> dict:
        with self.lock:
            open_n = sum(1 for s in self.sessions.values() if not s.closed)
            qd = float(sum(len(s.queue) for s in self.sessions.values()))
        p = self.latency.percentiles((99,))
        p99 = p.get("p99_ms")
        return {
            "ok": self.error is None,
            "error": self.error,
            "draining": self.draining,
            "open_sessions": open_n,
            "recoveries": 0,
            "fault_total": self.submit_errors,
            "stalls": 0,
            "warm_signatures": [self.key_render],
            "submit_errors": self.submit_errors,
            "wall_time_s": time.time(),
            "load": {
                "open_sessions": float(open_n),
                "queue_depth": qd,
                "p99_ms": p99 if p99 == p99 else None,
                "delivered_total": float(sum(
                    s.delivered for s in self.sessions.values())),
                "shed_total": 0.0,
                "slo_miss_total": 0.0,
                "admission_rejections_total": 0.0,
            },
        }

    def stats(self) -> dict:
        h = self.health()
        with self.lock:
            sessions = {
                sid: {"submitted": s.submitted, "delivered": s.delivered,
                      "queued": len(s.queue),
                      "state": "closed" if s.closed else "open"}
                for sid, s in self.sessions.items()
            }
        return {
            "stats": {
                "flavor": "multihost",
                "hosts": int(self.cfg["hosts"]),
                "engine_batches": self.batches,
                "engine_frames": self.frames,
                "open_sessions": h["open_sessions"],
                "queue_depth": h["load"]["queue_depth"],
                "errors": self.submit_errors,
                "recoveries": 0,
                "faults": {"by_kind": {}},
                "sessions": sessions,
                "aggregate": self.latency.summary(),
            },
            "latency": self.latency.snapshot(),
            "signals": {
                "delivered_total": h["load"]["delivered_total"],
                "queue_depth": h["load"]["queue_depth"],
            },
            "health": h,
        }

    # -- the data plane (batch thread) ------------------------------------

    def _batch_loop(self) -> None:
        import numpy as np

        from dvf_tpu.fleet.replica import recv_msg, send_msg
        from dvf_tpu.serve.session import Delivery

        cfg = self.cfg
        shape = tuple(cfg["frame_shape"])
        b_global = int(cfg["batch_global"])
        dtype = np.dtype(self.engine._signature[1])
        while not self._stop.is_set():
            if self.error is not None:
                return
            slots = []   # (session, local_index, ts, tag)
            with self.lock:
                live = [s for s in self.sessions.values() if s.queue]
                while live and len(slots) < b_global:
                    nxt = []
                    for s in live:         # round-robin fairness
                        if len(slots) >= b_global:
                            break
                        frame, ts, tag = s.queue.popleft()
                        slots.append((s, s.next_index, frame, ts, tag))
                        s.next_index += 1
                        if s.queue:
                            nxt.append(s)
                    live = nxt
            if not slots:
                time.sleep(0.002)
                continue
            batch = np.zeros((b_global, *shape), dtype)
            for row, (_, _, frame, _, _) in enumerate(slots):
                batch[row] = frame
            self.seq += 1
            try:
                # Peers first (their shards must be in flight before the
                # collective blocks this thread), then our own share.
                for pid, sock in self.peers:
                    send_msg(sock, ("batch", self.seq,
                                    self._rows(batch, pid)))
                local_out = np.asarray(self.engine.submit_local(
                    self._rows(batch, 0)))
                outs = {0: local_out}
                for pid, sock in self.peers:
                    reply = recv_msg(sock)
                    if reply[0] != "out" or reply[1] != self.seq:
                        raise ConnectionError(
                            f"peer {pid} desynchronized: {reply[:2]!r}")
                    outs[pid] = reply[2]
            except Exception as e:  # noqa: BLE001 — peer loss or wire
                # death: the group is broken as a unit; the fleet
                # replaces the whole replica (drain → respawn).
                self.submit_errors += len(slots)
                self.error = (f"group collective failed: {e!r}"
                              + (" [peer loss]" if _peer_loss(e) else ""))
                return
            out_global = np.empty((b_global, *local_out.shape[1:]),
                                  local_out.dtype)
            for pid, rows in outs.items():
                cursor = 0
                for start, stop in self.intervals[pid]:
                    out_global[start:stop] = rows[cursor:cursor
                                                  + (stop - start)]
                    cursor += stop - start
            now = time.time()
            with self.lock:
                for row, (s, idx, _, ts, tag) in enumerate(slots):
                    lat_s = max(0.0, now - ts)
                    self.latency.record(lat_s)
                    s.out.append(Delivery(
                        index=idx,
                        frame=np.ascontiguousarray(out_global[row]),
                        capture_ts=ts, latency_ms=lat_s * 1e3, tag=tag))
                    s.delivered += 1
                self.batches += 1
                self.frames += len(slots)

    def _rows(self, batch, pid: int):
        import numpy as np

        parts = [batch[start:stop] for start, stop in self.intervals[pid]]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def _proc_intervals(sharding, shape, n_procs: int) -> dict:
    """Per-process batch-row intervals under the compiled sharding —
    computed, never assumed (the device order is the mesh's business).
    Distinct devices holding one interval dedupe (replicated layouts);
    intervals come back sorted so slicing is in global row order."""
    by_proc: dict = {i: set() for i in range(n_procs)}
    for d, idx in sharding.devices_indices_map(tuple(shape)).items():
        sl = idx[0]
        by_proc[d.process_index].add(
            (sl.start or 0, shape[0] if sl.stop is None else sl.stop))
    return {pid: sorted(iv) for pid, iv in by_proc.items()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--parent-port", type=int, default=0)
    ap.add_argument("--peer-port", type=int, required=True)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--replica-id", default="r?")
    args = ap.parse_args(argv)
    cfg = json.loads(os.environ["DVF_MH_CONFIG"])

    import socket

    from dvf_tpu.fleet.replica import recv_msg, send_msg

    leader = args.process_id == 0
    parent = None
    peer_listener = None
    try:
        if leader:
            # Bind the data-plane listener BEFORE the distributed init:
            # peers connect right after their init returns, and init
            # itself only completes once every member (us included) has
            # joined — bind-early makes the two rendezvous independent.
            peer_listener = socket.socket()
            peer_listener.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
            peer_listener.bind((args.host, args.peer_port))
            peer_listener.listen(args.num_processes)
            peer_listener.settimeout(120.0)
            parent = socket.create_connection(
                (args.host, args.parent_port), timeout=30)
            parent.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_msg(parent, ("hello", os.getpid()))
            op = recv_msg(parent)
            if op[0] != "config":
                send_msg(parent, ("err", "ServeError",
                                  f"expected config, got {op[0]!r}"))
                return 2

        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception as e:  # noqa: BLE001 — old jax: no CPU
                raise RuntimeError(
                    f"no CPU collectives ({e}) — multihost replicas "
                    f"need jax with gloo support") from e

            from dvf_tpu.fleet.multiproc import MultiHostEngine
            from dvf_tpu.parallel.distributed import init_distributed
            from dvf_tpu.parallel.mesh import MeshConfig
            from dvf_tpu.runtime.signature import build_filter

            if not init_distributed(args.coordinator,
                                    args.num_processes, args.process_id):
                raise RuntimeError("init_distributed returned False "
                                   "(no coordinator address)")
            engine = MultiHostEngine(
                build_filter(cfg["op_chain"]),
                MeshConfig(data=args.num_processes))
            import numpy as np

            shape = (int(cfg["batch_global"]), *cfg["frame_shape"])
            engine.compile(shape, dtype=np.dtype(cfg["frame_dtype"]))
        except Exception as e:  # noqa: BLE001 — bring-up failure: the
            # leader reports it to the parent; peers just exit (the
            # leader's init fails with them, or times out)
            if leader and parent is not None:
                try:
                    send_msg(parent, ("err", type(e).__name__, str(e)))
                except Exception:  # noqa: BLE001
                    pass
            return 2

        if not leader:
            sock = socket.create_connection(
                (args.host, args.peer_port), timeout=120)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_msg(sock, ("join", args.process_id))
            # The lockstep loop: one shard in, one collective, one
            # shard out. A closed leader socket is the exit signal.
            while True:
                try:
                    msg = recv_msg(sock)
                except (ConnectionError, OSError):
                    return 0
                if msg[0] == "stop":
                    return 0
                _, seq, rows = msg
                out = engine.submit_local(rows)
                send_msg(sock, ("out", seq, np.asarray(out)))

        # -- leader: accept peers, then serve the replica RPC ------------
        peers = []
        for _ in range(args.num_processes - 1):
            psock, _ = peer_listener.accept()
            psock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            join = recv_msg(psock)
            if join[0] != "join":
                raise RuntimeError(f"bad peer join {join!r}")
            peers.append((join[1], psock))
        peers.sort()
        intervals = _proc_intervals(engine._sharding, shape,
                                    args.num_processes)
        srv = _Leader(engine, cfg, peers, intervals)
        send_msg(parent, ("ready", os.getpid()))

        while True:
            try:
                op = recv_msg(parent)
            except (ConnectionError, OSError):
                break  # parent went away: shut down with it
            kind = op[0]
            if kind == "submit1":
                _, sid, frame, ts, tag = op
                try:
                    srv.submit(sid, frame, ts=ts, tag=tag)
                except Exception as e:  # noqa: BLE001 — freshness-first
                    srv.submit_errors += 1
                    print(f"[mh-worker] submit dropped: {e!r}",
                          file=sys.stderr, flush=True)
                continue
            try:
                if kind == "stop":
                    send_msg(parent, ("ok", None))
                    break
                elif kind == "open":
                    _, sid, slo_ms, frame_shape, frame_dtype = op[:5]
                    out = srv.open_stream(
                        sid, slo_ms=slo_ms, frame_shape=frame_shape,
                        frame_dtype=frame_dtype or None,
                        op_chain=op[5] if len(op) > 5 else None,
                        tier=op[6] if len(op) > 6 else None)
                elif kind == "poll":
                    _, sid, max_items, meta_only = op
                    out = srv.poll(sid, max_items, meta_only=meta_only)
                elif kind == "close":
                    out = srv.close(op[1], drain=op[2])
                elif kind == "release":
                    out = srv.release(op[1])
                elif kind == "drain":
                    out = srv.drain(timeout=op[1])
                elif kind == "begin_drain":
                    out = srv.begin_drain()
                elif kind == "health":
                    out = srv.health()
                elif kind == "stats":
                    out = srv.stats()
                elif kind == "audit_probe":
                    out = srv.audit_probe(op[1] if len(op) > 1 else None)
                elif kind == "trace":
                    out = {"events": []}  # lean tier: no tracer lanes
                else:
                    raise ValueError(f"unknown replica op {kind!r}")
            except Exception as e:  # noqa: BLE001 — op errors cross the
                send_msg(parent, ("err", type(e).__name__, str(e)))
                continue
            send_msg(parent, ("ok", out))
        srv.stop()
        for _, psock in peers:
            try:
                send_msg(psock, ("stop",))
                psock.close()
            except OSError:
                pass
    finally:
        for s in (parent, peer_listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
