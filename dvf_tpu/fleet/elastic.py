"""Elastic fleet machinery: warm standby pool + the elasticity plane.

Two pieces, both owned by :class:`~dvf_tpu.fleet.router.FleetFrontend`
when ``FleetConfig.autoscale`` is armed:

:class:`StandbyPool`
    What makes ``spawn_replica()`` cheap enough to be a control action.
    A cold replica spawn is seconds of work — process fork, jax/XLA
    init, then a trace+compile per signature — which is exactly the
    window an overload burst needs to blow p99. The pool keeps
    ``warm_target`` replicas PRE-SPAWNED and AOT-PRECOMPILED (the
    ``--precompile`` manifest through the persistent compilation cache,
    PR 9) but not yet serving; adopting one into the fleet is a
    dictionary insert plus session placement — the measured
    spawn-to-first-served-frame gap in ``ELASTIC_BENCH.json``. A
    background refill thread replaces taken standbys, so the pool is
    warm again before the controller's cooldown expires.

:class:`ElasticFleetPlane`
    The loop wiring (the `control.plane.ControlPlane` discipline one
    tier up): hangs the deterministic
    `control.fleet_elastic.FleetElasticityController` off the fleet
    telemetry ring's ``on_sample`` seam, composes each flat row with
    the fleet's RPC-free ``elastic_view()``, decides inline on the
    sampler, and applies on a dedicated thread — a spawn that does end
    up cold-compiling (pool empty, multihost group bring-up) must
    never stall the sampling cadence the next decision reads. Keeps a
    bounded decision log AND the composed-row window, so the whole
    scaling episode replays deterministically from the recorded rows
    (the bench's ``replay.match`` acceptance).

Leak discipline: standby replicas are REAL worker processes (or live
frontends in local mode) that exist before any session does, so a pool
that outlives its fleet is a leaked child. ``live_standby_handles()``
is the conftest session-end guard's registry, the
``live_worker_processes`` pattern extended to standbys.
"""

from __future__ import annotations

import collections
import queue
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

from dvf_tpu.control.controllers import Action
from dvf_tpu.control.fleet_elastic import (
    FLAVOR_DEFAULT,
    ElasticConfig,
    make_elasticity_controller,
)
from dvf_tpu.fleet.replica import ReplicaHandle

# Live pools, for the conftest leak guard (weak: a collected pool's
# standbys were stopped by its owner or are already counted as leaked
# worker processes).
_LIVE_POOLS: "weakref.WeakSet[StandbyPool]" = weakref.WeakSet()


def live_standby_handles() -> List[ReplicaHandle]:
    """Warm standby replicas still held by un-stopped pools — the
    conftest session-end leak guard's registry (a standby outliving
    ``FleetFrontend.stop()`` is a leaked child)."""
    out: List[ReplicaHandle] = []
    for pool in list(_LIVE_POOLS):
        if not pool.closed:
            out.extend(pool.peek())
    return out


class StandbyPool:
    """Pre-spawned, AOT-warm replicas awaiting adoption (module
    docstring). ``spawn_fn()`` allocates a replica id, builds the
    handle, and must return it UNSTARTED — the pool pays the start
    (process fork + jax init + precompile) on its own refill thread so
    neither the caller nor the elastic apply thread ever does."""

    def __init__(self, spawn_fn: Callable[[], ReplicaHandle],
                 warm_target: int = 1):
        if warm_target < 1:
            raise ValueError("warm_target must be >= 1")
        self._spawn = spawn_fn
        self.warm_target = warm_target
        self.spawned_total = 0
        self.taken_total = 0
        self.spawn_errors_total = 0
        self._ready: "collections.deque[ReplicaHandle]" = collections.deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.closed = False
        _LIVE_POOLS.add(self)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "StandbyPool":
        if self._thread is not None:
            raise RuntimeError("standby pool already started")
        self._thread = threading.Thread(
            target=self._refill_loop, name="dvf-fleet-standby", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 15.0) -> None:
        self.closed = True
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        while True:
            with self._lock:
                if not self._ready:
                    break
                h = self._ready.popleft()
            try:
                h.stop(timeout=timeout)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- the pool ---------------------------------------------------------

    def take(self) -> Optional[ReplicaHandle]:
        """Pop one warm, already-started replica (None when the pool is
        momentarily dry — the caller falls back to a cold spawn) and
        wake the refill so the next take finds the pool warm again."""
        with self._lock:
            h = self._ready.popleft() if self._ready else None
            if h is not None:
                self.taken_total += 1
        self._wake.set()
        return h

    def peek(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._ready)

    @property
    def warm_count(self) -> int:
        with self._lock:
            return len(self._ready)

    def stats(self) -> dict:
        with self._lock:
            return {
                "warm": len(self._ready),
                "warm_target": self.warm_target,
                "spawned_total": self.spawned_total,
                "taken_total": self.taken_total,
                "spawn_errors_total": self.spawn_errors_total,
            }

    # -- refill thread ----------------------------------------------------

    def _refill_loop(self) -> None:
        backoff = 0.5
        while not self._stop.is_set():
            if self.warm_count >= self.warm_target:
                self._wake.wait(0.25)
                self._wake.clear()
                continue
            try:
                h = self._spawn()
                h.start()
            except Exception:  # noqa: BLE001 — a failed warm spawn is
                # retried with backoff; the fleet still works, spawns
                # are just cold until the pool recovers
                self.spawn_errors_total += 1
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 8.0)
                continue
            backoff = 0.5
            adopted = False
            with self._lock:
                if not self.closed:
                    self._ready.append(h)
                    self.spawned_total += 1
                    adopted = True
            if not adopted:
                # stop() raced the start: this standby would leak past
                # the sweep above — tear it down here instead.
                try:
                    h.stop(timeout=10.0)
                except Exception:  # noqa: BLE001
                    pass


class ElasticFleetPlane:
    """Controller wiring for one fleet (module docstring)."""

    def __init__(self, fleet: Any, config: Optional[ElasticConfig] = None,
                 decision_log: int = 256, record_window: int = 4096):
        self.fleet = fleet
        self.config = config or ElasticConfig()
        # Predictive (feed-forward) vs reactive is a config bit, decided
        # in ONE place so replay tooling rebuilds the same controller.
        self.controller = make_elasticity_controller(self.config)
        self._prev_row: Optional[dict] = None
        self._lock = threading.Lock()
        self.scale_out_total = 0
        self.scale_in_total = 0
        self.scale_errors_total = 0
        self.saturations_total = 0
        self.relay_out_total = 0
        self.relay_in_total = 0
        self.decisions: "collections.deque" = collections.deque(
            maxlen=decision_log)
        # The composed-row window + emitted actions: the deterministic
        # replay substrate (bench acceptance — a fresh controller over
        # ``window`` must reproduce ``actions`` byte-identically).
        self.window: "collections.deque[dict]" = collections.deque(
            maxlen=record_window)
        self.actions: "collections.deque[tuple]" = collections.deque(
            maxlen=record_window)
        self._apply_q: "queue.Queue[Optional[Action]]" = queue.Queue()
        self._apply_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ElasticFleetPlane":
        if self._apply_thread is not None:
            raise RuntimeError("elastic plane already started")
        self._stop.clear()
        self._apply_thread = threading.Thread(
            target=self._apply_loop, name="dvf-fleet-elastic-apply",
            daemon=True)
        self._apply_thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._apply_q.put(None)
        if self._apply_thread is not None:
            self._apply_thread.join(timeout=timeout)
            self._apply_thread = None

    # -- the ring seam ----------------------------------------------------

    def on_sample(self, prev: Optional[dict], cur: dict) -> None:
        """TimeSeriesRing hook: compose the fleet control row, decide,
        queue. The ring contains hook exceptions (``hook_errors_total``)
        but decide() is total by construction. ``desired`` moves at
        ENQUEUE time, not at apply completion: a spawn takes real wall
        time even warm, and the controller must see its own intent in
        the next row rather than double-firing into the gap."""
        del prev  # the controller tracks its own prev (replay parity)
        row = dict(cur)
        row.update(self.fleet.elastic_view())
        for a in self.decide(row):
            if a.kind in ("scale_out", "scale_in"):
                self.fleet.set_desired_replicas(int(a.value))
            self._apply_q.put(a)

    def decide(self, row: dict) -> List[Action]:
        """One deterministic decision step over a composed row; records
        the row and any actions for replay. Safe to call directly with
        recorded rows — the bench's replay harness does, through a
        FRESH controller."""
        prev = self._prev_row
        actions = self.controller.step(row, prev)
        self._prev_row = row
        with self._lock:
            self.window.append(dict(row))
            for a in actions:
                self.actions.append((a.kind, a.target, a.value, a.reason))
                self.decisions.append({"kind": a.kind, "target": a.target,
                                       "value": a.value, "reason": a.reason})
        return actions

    def replay_window(self) -> dict:
        """The recorded (composed rows, emitted actions) pair — what
        the bench replays through a fresh controller to prove the run
        is reproducible from its telemetry window."""
        with self._lock:
            return {"rows": [dict(r) for r in self.window],
                    "actions": list(self.actions)}

    # -- apply side -------------------------------------------------------

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            a = self._apply_q.get()
            if a is None:
                continue
            try:
                self._apply(a)
            except Exception:  # noqa: BLE001 — one failed scale action
                # must not kill the loop; counted, visible in stats
                with self._lock:
                    self.scale_errors_total += 1

    def _apply(self, a: Action) -> None:
        fleet = self.fleet
        if a.kind == "scale_out":
            flavor = None if a.target in (None, FLAVOR_DEFAULT) else a.target
            try:
                fleet.spawn_replica(flavor=flavor, cause="autoscale",
                                    reason=a.reason)
            except Exception:
                with self._lock:
                    self.scale_errors_total += 1
                fleet.rollback_desired(-1)
                return
            with self._lock:
                self.scale_out_total += 1
        elif a.kind == "scale_in":
            ok = False
            try:
                ok = fleet.retire_replica(a.target, cause="autoscale",
                                          reason=a.reason)
            finally:
                if not ok:
                    fleet.rollback_desired(+1)
            if ok:
                with self._lock:
                    self.scale_in_total += 1
        elif a.kind == "relay_out":
            # Third axis: a relay-only egress replica — no desired-
            # replicas bookkeeping to roll back (relays never count
            # against the filter-replica bounds).
            try:
                fleet.spawn_broadcast_relay(cause="autoscale",
                                            reason=a.reason)
            except Exception:
                with self._lock:
                    self.scale_errors_total += 1
                return
            with self._lock:
                self.relay_out_total += 1
        elif a.kind == "relay_in":
            if fleet.retire_broadcast_relay(a.target, cause="autoscale",
                                            reason=a.reason):
                with self._lock:
                    self.relay_in_total += 1
        elif a.kind == "flight":
            with self._lock:
                self.saturations_total += 1
            fleet.flight_trip(a.reason)

    # -- observability ----------------------------------------------------

    def signals(self) -> Dict[str, float]:
        """Flat counters for the fleet's ``signals()`` export."""
        with self._lock:
            return {
                "scale_out_total": float(self.scale_out_total),
                "scale_in_total": float(self.scale_in_total),
                "scale_errors_total": float(self.scale_errors_total),
                "scale_saturations_total": float(self.saturations_total),
                "relay_out_total": float(self.relay_out_total),
                "relay_in_total": float(self.relay_in_total),
            }

    def stats(self) -> dict:
        sig = self.signals()
        with self._lock:
            return {
                **{k: int(v) for k, v in sig.items()},
                "pending_applies": self._apply_q.qsize(),
                "window_rows": len(self.window),
                "decisions": list(self.decisions)[-32:],
            }
