"""On-disk plan cache — the auto-plan plane's persistence tier.

A sibling of the PR 9 persistent compilation cache and the PR 11 stage
profiles: where those store *compiled programs* and *measured stage
costs*, this stores the planner's *decisions* — the winning
:class:`~dvf_tpu.control.planner.Plan` for a (canonical signature,
geometry, topology fingerprint, planner version) key — plus the
compile-time calibrations (``h2d_block_ms`` / ``d2h_block_ms`` /
``step_block_ms``) keyed per (backend, topology fingerprint), so a warm
restart skips BOTH the candidate search and the blocking re-measurement
passes at engine compile.

Keying discipline (pinned by tests/test_planner.py): any change to the
op chain, the geometry, the device topology, or the planner's own
version misses — a plan searched on 8 TPU cores must never drive a
2-core host, and a planner whose candidate grid or scoring changed must
re-search rather than trust a stale winner. Corrupt or foreign cache
entries load as None (the caller re-plans); a broken cache file must
never crash a startup.

Same durability discipline as `obs.lineage`'s stage profiles: atomic
tmp+rename writes, one flock'd lock file per directory against
concurrent writers (N fleet replicas planning at once), best-effort
everywhere — plans are optimization state, never worth failing a serve
over.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Optional

# Bump when the Plan schema, the candidate grid, or the scoring model
# changes shape: a cached winner from an older planner must re-search,
# not silently drive the new runtime.
PLANNER_VERSION = 1

PLAN_SCHEMA = "dvf.plan_cache.v1"
CAL_SCHEMA = "dvf.plan_calibrations.v1"

DEFAULT_PLAN_CACHE_DIR = ".dvf_plan_cache"


# ---------------------------------------------------------------------------
# Topology fingerprint
# ---------------------------------------------------------------------------


def topology_fingerprint(mesh: Any = None) -> str:
    """A stable string for "what hardware, laid out how": backend +
    device kinds + device count + mesh axis shape. Two processes on
    identical hardware with the same mesh layout agree; adding a
    device, changing the backend, or resharding the mesh all miss —
    the plan-cache invalidation axis that keeps a plan searched on one
    topology from driving another. Never raises: on a backend that
    cannot even enumerate devices the fingerprint is ``"unknown"``
    (every lookup misses — correct, just cold)."""
    try:
        if mesh is not None:
            devs = list(mesh.devices.flat)
            axes = ",".join(f"{k}={v}" for k, v in dict(mesh.shape).items())
        else:
            import jax

            devs = list(jax.devices())
            # Meshless callers (the fleet front door plans before any
            # replica engine exists) must spell the axes exactly as an
            # Engine's DEFAULT mesh would on this hardware, or the
            # door could never hit a plan a serve frontend cached.
            from dvf_tpu.parallel.mesh import auto_mesh_config

            c = auto_mesh_config(len(devs))
            axes = f"data={c.data},space={c.space},model={c.model}"
        if not devs:
            return "unknown"
        backend = getattr(devs[0], "platform", "unknown")
        kinds = sorted({str(getattr(d, "device_kind", "?")) for d in devs})
        return f"{backend}/{'+'.join(kinds)}/n{len(devs)}/{axes}"
    except Exception:  # noqa: BLE001 — a fingerprint failure = cache cold
        return "unknown"


# ---------------------------------------------------------------------------
# Plan entries
# ---------------------------------------------------------------------------


def _plan_key(signature: str, geometry, topology: str,
              planner_version: int) -> str:
    geo = "x".join(str(int(d)) for d in tuple(geometry))
    raw = f"{signature}|{geo}|{topology}|v{int(planner_version)}"
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def plan_path(cache_dir: str, signature: str, geometry, topology: str,
              planner_version: int = PLANNER_VERSION) -> str:
    return os.path.join(
        cache_dir,
        f"plan-{_plan_key(signature, geometry, topology, planner_version)}"
        f".json")


def save_plan(cache_dir: str, signature: str, geometry, topology: str,
              plan_doc: dict,
              planner_version: int = PLANNER_VERSION) -> Optional[str]:
    """Persist one winning plan (atomic tmp+rename). The key fields are
    stored IN the entry too, so a load re-verifies them — a hash
    collision or a hand-edited file degrades to a miss, never to a
    foreign plan driving the runtime. Returns the path, or None when
    the write failed (best-effort)."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = plan_path(cache_dir, signature, geometry, topology,
                         planner_version)
        doc = {
            "schema": PLAN_SCHEMA,
            "planner_version": int(planner_version),
            "signature": signature,
            "geometry": [int(d) for d in tuple(geometry)],
            "topology": topology,
            "plan": dict(plan_doc),
            "updated": time.time(),
        }
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def load_plan(cache_dir: Optional[str], signature: str, geometry,
              topology: str,
              planner_version: int = PLANNER_VERSION) -> Optional[dict]:
    """The cached plan dict for this exact key, or None on a miss —
    where "miss" includes absent, unreadable, corrupt JSON, a foreign
    schema/planner version, and an entry whose embedded key fields
    disagree with the request (each pinned by tests/test_planner.py).
    Never raises: a broken cache entry re-plans, it does not crash
    startup."""
    if not cache_dir:
        return None
    try:
        with open(plan_path(cache_dir, signature, geometry, topology,
                            planner_version)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != PLAN_SCHEMA:
        return None
    if doc.get("planner_version") != int(planner_version):
        return None
    if doc.get("signature") != signature or doc.get("topology") != topology:
        return None
    if list(doc.get("geometry") or ()) != [int(d) for d in tuple(geometry)]:
        return None
    plan = doc.get("plan")
    return dict(plan) if isinstance(plan, dict) else None


# ---------------------------------------------------------------------------
# Compile-time calibrations (per backend+topology, per batch signature)
# ---------------------------------------------------------------------------


_CAL_KEYS = ("h2d_block_ms", "d2h_block_ms", "step_block_ms")


def calibration_path(cache_dir: str, topology: str) -> str:
    """One JSON file per (backend, topology) — the backend is part of
    the topology fingerprint — holding every batch signature's
    calibration triple measured on that hardware."""
    h = hashlib.sha256(topology.encode()).hexdigest()[:16]
    return os.path.join(cache_dir, f"plan-cal-{h}.json")


def save_calibrations(cache_dir: str, topology: str, signature: str,
                      cal: dict) -> Optional[str]:
    """Record one batch signature's measured calibration triple under
    its topology's file (read-merge-write under the directory flock —
    N replicas compiling different signatures share one file). Only
    the known keys persist; None values are kept (d2h is legitimately
    None above the calibration size cap, and a seed must reproduce
    that). Best-effort."""
    entry = {k: cal.get(k) for k in _CAL_KEYS}
    if all(v is None for v in entry.values()):
        return None
    lock_f = None
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = calibration_path(cache_dir, topology)
        try:
            import fcntl

            lock_f = open(os.path.join(cache_dir, ".plan-cache.lock"), "w")
            fcntl.flock(lock_f, fcntl.LOCK_EX)
        except (ImportError, OSError):
            lock_f = None
        doc = None
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            doc = None
        if (not isinstance(doc, dict) or doc.get("schema") != CAL_SCHEMA
                or doc.get("topology") != topology
                or not isinstance(doc.get("signatures"), dict)):
            doc = {"schema": CAL_SCHEMA, "topology": topology,
                   "signatures": {}}
        doc["signatures"][signature] = entry
        doc["updated"] = time.time()
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
    finally:
        if lock_f is not None:
            try:
                lock_f.close()
            except OSError:
                pass


def load_calibrations(cache_dir: Optional[str], topology: str,
                      signature: str) -> Optional[dict]:
    """One batch signature's calibration triple for this topology, or
    None on any miss/corruption (the compile re-measures — the cold
    path is always correct)."""
    if not cache_dir:
        return None
    try:
        with open(calibration_path(cache_dir, topology)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if (not isinstance(doc, dict) or doc.get("schema") != CAL_SCHEMA
            or doc.get("topology") != topology):
        return None
    entry = (doc.get("signatures") or {}).get(signature)
    if not isinstance(entry, dict):
        return None
    out = {k: entry.get(k) for k in _CAL_KEYS}
    # A seed must carry a real step cost — it is what the analytic
    # scorer and the bucket scheduler start from; h2d alone is not
    # worth skipping the measurement passes for.
    if not isinstance(out.get("step_block_ms"), (int, float)):
        return None
    if not isinstance(out.get("h2d_block_ms"), (int, float)):
        return None
    return out
