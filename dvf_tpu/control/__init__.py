"""Load-adaptive control plane — the fifth tier, above supervision.

PR 4 made the runtime degrade gracefully on *faults*; this package makes
it degrade gracefully on *load*: closed-loop controllers read the
telemetry ring PR 8 built (queue depth, SLO headroom, fps, p99 at a
fixed cadence) and actuate the knobs the runtime already exposes —
per-bucket batch size and the dispatch tick budget, per-session
resolution (with the ``ops/sr.py`` upscale stage restoring full
client-visible resolution), and priority-tier admission — so a traffic
burst past capacity bends p99 instead of collapsing it.

See `control.controllers` for the decision logic (deterministic,
replayable) and `control.plane` for the loop wiring.
"""

from dvf_tpu.control.controllers import (
    TIER_BATCH,
    TIER_INTERACTIVE,
    TIER_NAMES,
    TIER_STANDARD,
    Action,
    BatchTickController,
    ControlConfig,
    QualityController,
    TierAdmissionController,
    is_pressure,
)
from dvf_tpu.control.fleet_elastic import (
    FLAVOR_DEFAULT,
    FLAVOR_MULTIHOST,
    ElasticConfig,
    FleetElasticityController,
    PredictiveElasticityController,
    fleet_pressure,
    make_elasticity_controller,
)
from dvf_tpu.control.plan_cache import (
    PLANNER_VERSION,
    load_calibrations,
    load_plan,
    save_calibrations,
    save_plan,
    topology_fingerprint,
)
from dvf_tpu.control.plane import ControlPlane
from dvf_tpu.control.planner import (
    DEFAULT_PLAN,
    Plan,
    analytic_frame_ms,
    candidate_grid,
    plan_from_cache,
    plan_search,
    plan_to_cache,
    predicted_tick_cost_ms,
    shortlist,
)

__all__ = [
    "Action",
    "BatchTickController",
    "ControlConfig",
    "ControlPlane",
    "DEFAULT_PLAN",
    "ElasticConfig",
    "FLAVOR_DEFAULT",
    "FLAVOR_MULTIHOST",
    "FleetElasticityController",
    "PLANNER_VERSION",
    "Plan",
    "PredictiveElasticityController",
    "QualityController",
    "TierAdmissionController",
    "TIER_BATCH",
    "TIER_INTERACTIVE",
    "TIER_NAMES",
    "TIER_STANDARD",
    "analytic_frame_ms",
    "candidate_grid",
    "fleet_pressure",
    "is_pressure",
    "load_calibrations",
    "load_plan",
    "make_elasticity_controller",
    "plan_from_cache",
    "plan_search",
    "plan_to_cache",
    "predicted_tick_cost_ms",
    "save_calibrations",
    "save_plan",
    "shortlist",
    "topology_fingerprint",
]
