"""Load-adaptive control plane — the fifth tier, above supervision.

PR 4 made the runtime degrade gracefully on *faults*; this package makes
it degrade gracefully on *load*: closed-loop controllers read the
telemetry ring PR 8 built (queue depth, SLO headroom, fps, p99 at a
fixed cadence) and actuate the knobs the runtime already exposes —
per-bucket batch size and the dispatch tick budget, per-session
resolution (with the ``ops/sr.py`` upscale stage restoring full
client-visible resolution), and priority-tier admission — so a traffic
burst past capacity bends p99 instead of collapsing it.

See `control.controllers` for the decision logic (deterministic,
replayable) and `control.plane` for the loop wiring.
"""

from dvf_tpu.control.controllers import (
    TIER_BATCH,
    TIER_INTERACTIVE,
    TIER_NAMES,
    TIER_STANDARD,
    Action,
    BatchTickController,
    ControlConfig,
    QualityController,
    TierAdmissionController,
    is_pressure,
)
from dvf_tpu.control.fleet_elastic import (
    FLAVOR_DEFAULT,
    FLAVOR_MULTIHOST,
    ElasticConfig,
    FleetElasticityController,
    fleet_pressure,
)
from dvf_tpu.control.plane import ControlPlane

__all__ = [
    "Action",
    "BatchTickController",
    "ControlConfig",
    "ControlPlane",
    "ElasticConfig",
    "FLAVOR_DEFAULT",
    "FLAVOR_MULTIHOST",
    "FleetElasticityController",
    "QualityController",
    "TierAdmissionController",
    "TIER_BATCH",
    "TIER_INTERACTIVE",
    "TIER_NAMES",
    "TIER_STANDARD",
    "fleet_pressure",
    "is_pressure",
]
