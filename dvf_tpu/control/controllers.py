"""Closed-loop controllers: telemetry rows in, actuation decisions out.

Each controller is a DETERMINISTIC transducer: ``step(row)`` consumes one
telemetry row (a flat signal dict plus the ``buckets``/``sessions`` view
the control plane attaches) and returns a list of :class:`Action`
records. No wall-clock reads, no randomness — replaying the same row
sequence through a fresh controller yields byte-identical action
sequences (pinned in tests/test_control.py), which is what makes an
overload incident reproducible from its flight-recorder window.

The three controllers map to the three knobs the serving runtime
already exposes:

:class:`BatchTickController`
    Per-bucket batch size from measured batch OCCUPANCY (mean valid
    rows per tick — a small bucket stops inheriting the big bucket's
    batch size, closing PR 9's per-bucket autotune item), growing under
    standing queue pressure; plus the dispatch tick interval (the tick
    budget: tighten while work is queued, relax when idle). Resizes
    actuate through the compile-aside HOT SWAP (the successor program
    compiles in the background, the commit is one pointer swing between
    ticks), so the hysteresis is safety-only: a short hold debounces
    the occupancy EWMA, a short flip dwell keeps the ladder from
    chattering, and SHRINKS are refused only during an overload episode
    (pressure OR a raised admission floor: floor-up calm is fake calm,
    and the shrink it invites is un-shrunk seconds later by the
    re-admission flood).

:class:`QualityController`
    Per-session resolution downshift under sustained pressure, lowest
    tier first; the session's op chain gains an ``upscale`` stage so
    clients still receive full-resolution frames (ops/sr.py). Recovery
    steps back up highest tier first. Hysteresis is explicit:
    ``down_after`` consecutive pressured samples per downshift,
    ``up_after`` recovered samples per upshift, a per-session
    ``min_dwell`` between OPPOSITE-direction moves — a session can
    never oscillate within one dwell window — and no upshift at all
    while the admission floor is raised (floor-up calm is fake calm:
    the system keeps up only because load is refused at the door).

:class:`TierAdmissionController`
    The admission floor: sustained overload first refuses new
    batch-tier sessions, then standard — paid/interactive tenants are
    shed last, at the door, before anyone's frames are. Release is
    STEPWISE (one tier per calm run): dropping the whole floor at once
    would re-admit the entire refused backlog as a flood that
    immediately re-trips the overload it was shed for.

The pattern is the profiling-driven adaptive-inference loop
(arXiv:2605.25682) with TVM's measured-stage discipline
(arXiv:1802.04799): every decision divides by a MEASURED signal
(occupancy EWMAs, measured tick costs, the telemetry ring's observed
queue depth and SLO headroom), never a guess.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# Priority tiers: lower value = higher priority = shed last.
TIER_INTERACTIVE, TIER_STANDARD, TIER_BATCH = 0, 1, 2
TIER_NAMES = {TIER_INTERACTIVE: "interactive", TIER_STANDARD: "standard",
              TIER_BATCH: "batch"}


@dataclasses.dataclass(frozen=True)
class Action:
    """One actuation decision. ``kind``: resize | tick | downshift |
    upshift | tier_floor | flight. ``target``: bucket label / session id
    / None. ``value``: the new setting. ``reason`` is human-readable and
    lands in the decision log the flight recorder dumps."""

    kind: str
    target: Optional[str]
    value: object
    reason: str


@dataclasses.dataclass
class ControlConfig:
    """Knobs for the whole control plane (CLI: ``--control``)."""

    interval_s: float = 1.0        # telemetry sampling cadence the plane
    #   arms the ring at (when nothing else armed it already)
    # -- pressure predicate (shared by all three controllers) ------------
    queue_high_per_session: float = 3.0   # standing queue_depth per open
    #   session that reads as overload (above one batch's worth of
    #   backlog per tenant, the system is not keeping up)
    headroom_low_ms: float = 0.0   # slo_headroom_ms below this = pressure
    # -- batch/tick controller ------------------------------------------
    batch_ladder: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    batch_max: int = 0             # 0 = the frontend's configured
    #   batch_size (set by the plane at attach)
    occupancy_headroom: float = 1.3   # size to EWMA occupancy × this
    # The resize hysteresis below was sized for the QUIESCE era, when
    # every resize paused its bucket for a recompile and a wrong
    # decision cost two visible stalls. Resizes now ride the
    # compile-aside hot swap (runtime.engine.prepare_swap/commit_swap):
    # the successor compiles on a background thread while the bucket
    # keeps serving, and the commit is one pointer swing between ticks
    # (~0 ms). The dwell values therefore shrink to safety-only floors —
    # enough to debounce a noisy occupancy EWMA, not to amortize a
    # stall that no longer exists.
    resize_hold: int = 2           # consecutive samples agreeing on the
    #   same target before a resize is issued (debounce only)
    resize_cooldown: int = 4       # min samples between resizes/bucket
    resize_flip_dwell: int = 8     # min samples before a bucket may
    #   resize in the OPPOSITE direction of its last resize (a
    #   flip now wastes only background compile, not serving time —
    #   this floor just keeps the ladder from chattering)
    tick_busy_s: float = 0.002     # dispatch tick while work is queued
    tick_idle_s: float = 0.01      # relaxed tick after idle_after
    idle_after: int = 5            # samples with zero queue before relax
    # -- quality controller ---------------------------------------------
    max_level: int = 1             # downshift steps (each is ×2 per axis)
    down_after: int = 3            # pressured samples per downshift step
    up_after: int = 8              # recovered samples per upshift step
    min_dwell: int = 16            # min samples between opposite-
    #   direction moves for ONE session (the no-oscillation bound)
    # -- tier admission controller --------------------------------------
    tier_floor_enabled: bool = True
    overload_after: int = 5        # pressured samples before the floor
    #   drops to refuse batch tier; 2× that refuses standard too
    # -- saturation ------------------------------------------------------
    saturate_after: int = 10       # pressured samples with every
    #   downshiftable session already at max_level → flight dump


def is_pressure(row: dict, prev: Optional[dict],
                config: ControlConfig) -> bool:
    """THE overload predicate, stated once: standing queue beyond
    ``queue_high_per_session`` per open session, OR negative SLO
    headroom, OR sheds/drops advancing since the previous row."""
    open_sessions = max(1.0, float(row.get("open_sessions") or 0.0))
    qd = float(row.get("queue_depth") or 0.0)
    if qd >= config.queue_high_per_session * open_sessions:
        return True
    headroom = row.get("slo_headroom_ms")
    if headroom is not None and float(headroom) < config.headroom_low_ms:
        # slo_headroom_ms is derived from LIFETIME percentiles (the
        # decimated latency reservoir never windows), so after a severe
        # burst it can stay negative long after the overload ended —
        # taken alone it would latch pressure and block recovery
        # indefinitely. When the row carries the delivery counters,
        # negative headroom reads as CURRENT pressure only while this
        # window's deliveries are still missing their SLO.
        cur_m = row.get("slo_miss_total")
        prev_m = None if prev is None else prev.get("slo_miss_total")
        if cur_m is None or prev_m is None:
            return True
        if float(cur_m) > float(prev_m):
            return True
    if prev is not None:
        for k in ("shed_total", "dropped_at_ingress_total"):
            cur_v, prev_v = row.get(k), prev.get(k)
            if cur_v is not None and prev_v is not None \
                    and float(cur_v) > float(prev_v):
                return True
    return False


class BatchTickController:
    """Per-bucket batch size from occupancy + the dispatch tick budget
    (class docstring in the module header)."""

    def __init__(self, config: ControlConfig):
        self.config = config
        self._i = 0                                   # sample index (the
        #   flip-dwell clock)
        self._want: Dict[str, Tuple[int, int]] = {}   # label -> (target,
        #   consecutive samples agreeing) — the resize_hold debounce
        self._cooldown: Dict[str, int] = {}           # label -> samples
        #   remaining before this bucket may resize again
        self._last_resize: Dict[str, Tuple[int, int]] = {}  # label ->
        #   (sample idx, direction): +1 grow, -1 shrink — the flip-dwell
        #   bookkeeping
        self._idle_streak = 0
        self._tick: Optional[float] = None            # last issued tick

    def _ladder_fit(self, occupancy: float, cap: int) -> int:
        want = occupancy * self.config.occupancy_headroom
        for n in self.config.batch_ladder:
            if n >= want:
                return min(n, cap)
        return cap

    def step(self, row: dict, prev: Optional[dict],
             floor: Optional[int] = None) -> List[Action]:
        """``floor``: the admission floor in force for this sample — a
        raised floor marks an overload episode even when the window
        itself reads calm (load is being refused at the door), and no
        bucket shrinks during an episode."""
        self._i += 1
        out: List[Action] = []
        cfg = self.config
        pressure = is_pressure(row, prev, cfg)
        seen = set()
        for b in row.get("buckets") or ():
            label = b.get("label")
            cur = b.get("batch_size")
            occ = b.get("mean_valid_rows")
            if label is None or cur is None:
                continue
            seen.add(label)
            cd = self._cooldown.get(label, 0)
            if cd > 0:
                self._cooldown[label] = cd - 1
            if occ is None:
                continue  # no measured ticks yet — never act on a guess
            cap = cfg.batch_max if cfg.batch_max > 0 else int(cur)
            target = self._ladder_fit(float(occ), cap)
            if float(b.get("queue_depth") or 0.0) > 2.0 * cur:
                # Standing backlog beyond two batches: throughput mode —
                # grow toward the cap regardless of what occupancy
                # (bounded by the CURRENT size) says.
                target = max(target, min(int(cur) * 2, cap))
            if target < cur and (pressure or floor is not None):
                # Never shrink during an overload episode (the calm a
                # raised floor buys is fake calm). Interactive tenants
                # no longer block a shrink: a hot-swapped resize costs
                # the bucket ~0 serving time, so reclaiming padded-row
                # compute is safe even under a tier-0 session.
                target = int(cur)
            if target == cur:
                self._want.pop(label, None)
                continue
            direction = 1 if target > cur else -1
            last = self._last_resize.get(label)
            if last is not None and last[1] != direction \
                    and (self._i - last[0]) < cfg.resize_flip_dwell:
                self._want.pop(label, None)   # opposite move too soon —
                continue                      # wait out the flip dwell
            prev_want, streak = self._want.get(label, (None, 0))
            streak = streak + 1 if prev_want == target else 1
            self._want[label] = (target, streak)
            if streak >= cfg.resize_hold and self._cooldown.get(label, 0) <= 0:
                out.append(Action(
                    "resize", label, target,
                    f"occupancy {float(occ):.1f} rows, queue "
                    f"{b.get('queue_depth')}, batch {cur} -> {target}"))
                self._cooldown[label] = cfg.resize_cooldown
                self._last_resize[label] = (self._i, direction)
                self._want.pop(label, None)
        for label in list(self._want):
            if label not in seen:
                del self._want[label]    # bucket retired
        for label in list(self._cooldown):
            if label not in seen:
                del self._cooldown[label]
        for label in list(self._last_resize):
            if label not in seen:
                del self._last_resize[label]
        # Tick budget: tighten the dispatch tick the moment work is
        # standing; relax only after a sustained idle run.
        qd = float(row.get("queue_depth") or 0.0)
        self._idle_streak = self._idle_streak + 1 if qd == 0 else 0
        tick = (cfg.tick_idle_s if self._idle_streak >= cfg.idle_after
                else cfg.tick_busy_s)
        if tick != self._tick:
            self._tick = tick
            out.append(Action("tick", None, tick,
                              f"queue_depth {qd:g}, idle_streak "
                              f"{self._idle_streak}"))
        return out


class QualityController:
    """Per-session resolution downshift/upshift with explicit
    hysteresis (module docstring)."""

    def __init__(self, config: ControlConfig):
        self.config = config
        self._i = 0                      # sample index (the dwell clock)
        self._pressure_streak = 0
        self._recover_streak = 0
        self._last_move: Dict[str, Tuple[int, int]] = {}  # sid -> (idx,
        #   direction): +1 downshift, -1 upshift — the dwell bookkeeping
        self.saturated_streak = 0        # read by the plane's
        #   saturation watch

    def _may_move(self, sid: str, direction: int) -> bool:
        last = self._last_move.get(sid)
        if last is None:
            return True
        idx, d = last
        if d == direction:
            return True   # same direction: the streak gates already
        return (self._i - idx) >= self.config.min_dwell

    def step(self, row: dict, prev: Optional[dict],
             floor: Optional[int] = None) -> List[Action]:
        """``floor``: the admission floor in force when this sample was
        taken (None = all tiers admitted). While a floor is raised, the
        calm the window shows is FAKE calm — the system is keeping up
        only because load is being refused at the door — so quality
        recovery must not begin: upshifting (interactive first, to its
        most expensive configuration) in the same breath as the floor
        releasing re-admits the flood straight onto freshly full-price
        sessions, the worst phase of the admission limit cycle. Release
        order is therefore: floor first, then — only if the window
        stays calm with every tier admitted — quality."""
        self._i += 1
        cfg = self.config
        sessions = list(row.get("sessions") or ())
        live = {s["sid"] for s in sessions}
        for sid in list(self._last_move):
            if sid not in live:
                del self._last_move[sid]
        pressure = is_pressure(row, prev, cfg)
        if pressure:
            self._pressure_streak += 1
            self._recover_streak = 0
        else:
            self._recover_streak += 1
            self._pressure_streak = 0
        out: List[Action] = []
        if pressure and self._pressure_streak >= cfg.down_after:
            # Downshift the LOWEST-priority tier (highest value) that
            # still has headroom, one step, all its eligible sessions at
            # once — gradual per-session trickles would take minutes to
            # bend a fleet-wide overload.
            movable = [s for s in sessions
                       if s.get("downshiftable")
                       and int(s.get("level") or 0) < cfg.max_level
                       and self._may_move(s["sid"], +1)]
            if movable:
                tier = max(int(s.get("tier") or 0) for s in movable)
                victims = sorted(
                    (s for s in movable if int(s.get("tier") or 0) == tier),
                    key=lambda s: s["sid"])
                for s in victims:
                    lvl = int(s.get("level") or 0) + 1
                    out.append(Action(
                        "downshift", s["sid"], lvl,
                        f"sustained pressure x{self._pressure_streak}, "
                        f"tier {TIER_NAMES.get(tier, tier)} -> level {lvl}"))
                    self._last_move[s["sid"]] = (self._i, +1)
                # Next round needs a fresh pressure run — EXCEPT under
                # severe pressure (standing queue at 2× the overload
                # threshold: a step overload's onset), where waiting out
                # a full streak per tier-by-tier round stretches the
                # bend across seconds of queue growth; severe rounds run
                # on consecutive pressured samples instead. Per-session
                # dwell still rules out oscillation — successive rounds
                # move DIFFERENT tiers.
                open_n = max(1.0, float(row.get("open_sessions") or 0.0))
                severe = float(row.get("queue_depth") or 0.0) \
                    >= 2.0 * cfg.queue_high_per_session * open_n
                self._pressure_streak = cfg.down_after - 1 if severe else 0
            else:
                # Nothing left to give: every downshiftable session is
                # at max level (or dwell-locked) while pressure holds —
                # the saturation signal the plane turns into a flight
                # dump past saturate_after.
                self.saturated_streak += 1
        else:
            if not pressure:
                self.saturated_streak = 0
        if not pressure and self._recover_streak >= cfg.up_after \
                and floor is None:
            down = [s for s in sessions if int(s.get("level") or 0) > 0
                    and self._may_move(s["sid"], -1)]
            if down:
                # Recover the HIGHEST-priority tier first (LIFO of the
                # downshift order: interactive gets its pixels back
                # before batch does).
                tier = min(int(s.get("tier") or 0) for s in down)
                winners = sorted(
                    (s for s in down if int(s.get("tier") or 0) == tier),
                    key=lambda s: s["sid"])
                for s in winners:
                    lvl = int(s.get("level") or 0) - 1
                    out.append(Action(
                        "upshift", s["sid"], lvl,
                        f"recovered x{self._recover_streak}, tier "
                        f"{TIER_NAMES.get(tier, tier)} -> level {lvl}"))
                    self._last_move[s["sid"]] = (self._i, -1)
                self._recover_streak = 0
        return out


class TierAdmissionController:
    """The admission floor under sustained overload (module docstring).
    Floor semantics: sessions with tier > floor are refused at
    open_stream; ``None`` admits every tier."""

    def __init__(self, config: ControlConfig):
        self.config = config
        self._pressure_streak = 0
        self._recover_streak = 0
        self._floor: Optional[int] = None

    @property
    def floor(self) -> Optional[int]:
        """The admission floor currently in force (None = open)."""
        return self._floor

    def step(self, row: dict, prev: Optional[dict]) -> List[Action]:
        cfg = self.config
        if not cfg.tier_floor_enabled:
            return []
        if is_pressure(row, prev, cfg):
            self._pressure_streak += 1
            self._recover_streak = 0
        else:
            self._recover_streak += 1
            self._pressure_streak = 0
        floor = self._floor
        if self._pressure_streak >= 2 * cfg.overload_after:
            floor = TIER_INTERACTIVE      # only interactive admits
        elif self._pressure_streak >= cfg.overload_after:
            floor = TIER_STANDARD         # batch tier refused
        elif self._recover_streak >= cfg.up_after and floor is not None:
            # STEPWISE release, one tier per calm run: dropping the
            # whole floor at once re-admits the entire refused backlog
            # as a flood that immediately re-trips the overload it was
            # shed for (the classic admission limit cycle) — re-admit
            # standard first, and only open batch after the window
            # stays calm WITH standard traffic back.
            floor = None if floor >= TIER_STANDARD else floor + 1
            self._recover_streak = 0   # each step judged on fresh calm
        if floor != self._floor:
            self._floor = floor
            return [Action(
                "tier_floor", None, floor,
                f"pressure_streak {self._pressure_streak}, "
                f"recover_streak {self._recover_streak}")]
        return []
