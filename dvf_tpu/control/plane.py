"""The control plane: telemetry in, actuation out, observable always.

``ControlPlane`` closes the loop PR 8 left open: the serving frontend's
``TimeSeriesRing`` already samples the load-control signals (fps,
p50/p99, queue depth, SLO headroom, per-kind fault rates) at a fixed
cadence — this module hangs the controllers (`control.controllers`) off
that ring's ``on_sample`` seam, composes each flat row with the
frontend's per-bucket/per-session control view, runs the DETERMINISTIC
decision step inline in the sampler, and applies the resulting actions
on a dedicated apply thread (an actuation that recompiles a program —
a per-bucket batch resize, a quality-bucket creation — must not stall
the sampling cadence the next decision depends on).

Dataflow (one arrow per thread boundary)::

  TimeSeriesRing (1/interval_s)
      └─ on_sample(prev, row) ──► ControlPlane.observe
             row + actuator.control_view()            [sampler thread]
             controllers.step(row) -> [Action]        (deterministic)
             decision log (bounded ring, flight-dumpable)
      └────── apply queue ──────► _apply_loop         [apply thread]
                  actuator.request_batch_size / set_tick_interval /
                  request_session_quality / set_admission_tier_floor

The actuator is duck-typed (ServeFrontend implements it) so the
controllers can be driven from recorded windows in tests without a
frontend — replaying the same rows twice yields the identical action
sequence, pinned by the tier-1 ``control`` marker tests.

Saturation: when the quality controller has nothing left to shed
(every downshiftable session at max level) while pressure persists
``saturate_after`` samples, the plane triggers the flight recorder —
"the controller gave everything it had and it wasn't enough" is
exactly when a post-mortem window is worth a dump.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
from typing import Any, List, Optional

from dvf_tpu.control.controllers import (
    Action,
    BatchTickController,
    ControlConfig,
    QualityController,
    TierAdmissionController,
    is_pressure,
)


class ControlPlane:
    """Owns the controllers and the apply thread (module docstring)."""

    def __init__(self, actuator: Any,
                 config: Optional[ControlConfig] = None,
                 decision_log: int = 256):
        self.actuator = actuator
        self.config = config or ControlConfig()
        self.batch = BatchTickController(self.config)
        self.quality = QualityController(self.config)
        self.tiers = TierAdmissionController(self.config)
        self._prev_row: Optional[dict] = None
        self._lock = threading.Lock()
        # Counters (exported through the owner's signals()/stats()).
        self.actions_total = 0
        self.downshifts_total = 0
        self.upshifts_total = 0
        self.batch_resizes_total = 0
        self.tick_changes_total = 0
        self.tier_floor_changes_total = 0
        self.saturations_total = 0
        self.apply_errors_total = 0
        self.rejected_quality_total = 0   # quality requests the actuator
        #   could not satisfy (bucket cap, odd geometry, session gone)
        self.tier_floor: Optional[int] = None
        self.tick_s: Optional[float] = None
        self._saturation_open = False     # one dump per episode
        # Bounded decision log: what the flight dump carries so "why did
        # the controller do that at 14:02" has an artifact.
        self.decisions: "collections.deque" = collections.deque(
            maxlen=decision_log)
        self._apply_q: "queue.Queue[Optional[Action]]" = queue.Queue()
        self._apply_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Auto-plan quiescence: while the planner drives the actuators
        # through its own measured search, the reactive loops must not
        # fight it (a batch controller sizing to the measurement
        # session's occupancy would undo every candidate's hot swap).
        self.paused = False

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ControlPlane":
        if self._apply_thread is not None:
            raise RuntimeError("control plane already started")
        self._stop.clear()
        self._apply_thread = threading.Thread(
            target=self._apply_loop, name="dvf-control-apply", daemon=True)
        self._apply_thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._apply_q.put(None)
        if self._apply_thread is not None:
            self._apply_thread.join(timeout=timeout)
            self._apply_thread = None

    # -- the operating envelope (auto-plan plane) ------------------------

    def apply_envelope(self, envelope: dict,
                       reason: Optional[str] = None) -> None:
        """Adopt a planner-chosen operating envelope
        (``control.planner.Plan.envelope()``): the batch ladder bounded
        at the planned batch, the planned tick as the busy tick. The
        controllers keep their closed-loop roles — sizing batch to
        measured occupancy, shedding under pressure — but now adapt
        WITHIN the measured-optimal envelope instead of rediscovering
        it from hard-coded defaults every run. Rebuilds the controllers
        against the new config; meant for startup (before traffic) — a
        concurrent decision step sees either the old or the new
        controller set, both total."""
        kw = {}
        ladder = envelope.get("batch_ladder")
        if ladder:
            kw["batch_ladder"] = tuple(int(b) for b in ladder)
        if envelope.get("batch_max"):
            kw["batch_max"] = int(envelope["batch_max"])
        if envelope.get("tick_busy_s"):
            kw["tick_busy_s"] = float(envelope["tick_busy_s"])
        if not kw:
            return
        cfg = dataclasses.replace(self.config, **kw)
        self.config = cfg
        self.batch = BatchTickController(cfg)
        self.quality = QualityController(cfg)
        self.tiers = TierAdmissionController(cfg)
        with self._lock:
            self.decisions.append({"kind": "envelope", "target": None,
                                   "value": dict(kw), "reason": reason})

    # -- the ring seam ---------------------------------------------------

    def on_sample(self, prev: Optional[dict], cur: dict) -> None:
        """TimeSeriesRing hook: compose the control row, decide, queue
        the actions. Exceptions are contained by the ring
        (``hook_errors_total``) — a broken controller must not kill the
        sampler — but decide() is total by construction."""
        if self.paused:
            return
        row = dict(cur)
        row.update(self.actuator.control_view())
        for a in self.decide(row):
            self._apply_q.put(a)

    def decide(self, row: dict) -> List[Action]:
        """One deterministic decision step over a composed row. Safe to
        call directly with recorded rows (the determinism tests do)."""
        prev = self._prev_row
        actions: List[Action] = []
        # Batch sees the floor too: a raised floor marks an overload
        # episode, and no bucket shrink-resizes during an episode (the
        # recompile stall would land on the very tenants being
        # protected).
        actions.extend(self.batch.step(row, prev,
                                       floor=self.tiers.floor))
        # Quality sees the floor as of ENTERING this step (tiers runs
        # after): a floor releasing this very sample still gates the
        # upshift, so quality recovery starts at least one full sample
        # after admission reopens — never into the re-admission flood.
        actions.extend(self.quality.step(row, prev,
                                         floor=self.tiers.floor))
        actions.extend(self.tiers.step(row, prev))
        # Saturation watch: quality has nothing left while pressure
        # holds. One flight action per episode (reset on recovery).
        if self.quality.saturated_streak >= self.config.saturate_after:
            if not self._saturation_open:
                self._saturation_open = True
                actions.append(Action(
                    "flight", None, None,
                    f"controller saturated: every downshiftable session "
                    f"at max level {self.config.max_level} with pressure "
                    f"sustained {self.quality.saturated_streak} samples"))
        elif self.quality.saturated_streak == 0 \
                and not is_pressure(row, prev, self.config):
            self._saturation_open = False
        self._prev_row = row
        if actions:
            # Measured stage-cost annotation (obs.lineage via the
            # actuator's control_view): a bucket-targeted decision
            # records WHERE that bucket's latency was going when the
            # controller acted — the decision log's half of "why did
            # the controller do that at 14:02".
            cost_by_label = {b.get("label"): b.get("stage_cost_ms")
                             for b in row.get("buckets") or []
                             if isinstance(b, dict)}
            with self._lock:
                self.actions_total += len(actions)
                for a in actions:
                    entry = {"kind": a.kind, "target": a.target,
                             "value": a.value, "reason": a.reason}
                    cost = cost_by_label.get(a.target)
                    if cost:
                        entry["stage_cost_ms"] = cost
                    self.decisions.append(entry)
        return actions

    # -- apply side ------------------------------------------------------

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            a = self._apply_q.get()
            if a is None:
                continue
            try:
                self._apply(a)
            except Exception:  # noqa: BLE001 — one failed actuation must
                with self._lock:   # not kill the loop; counted, loud in
                    self.apply_errors_total += 1   # stats, never raised
                    #   into the serving path

    def _apply(self, a: Action) -> None:
        act = self.actuator
        if a.kind == "resize":
            # The decision rationale rides into the actuator so the
            # reconfiguration ledger's batch_resize event records WHY
            # ("why did the controller do that at 14:02" — one artifact).
            act.request_batch_size(a.target, int(a.value), reason=a.reason)
            with self._lock:
                self.batch_resizes_total += 1
        elif a.kind == "tick":
            act.set_tick_interval(float(a.value))
            with self._lock:
                self.tick_changes_total += 1
                self.tick_s = float(a.value)
        elif a.kind in ("downshift", "upshift"):
            ok = act.request_session_quality(a.target, int(a.value),
                                             reason=a.reason)
            with self._lock:
                if not ok:
                    self.rejected_quality_total += 1
                elif a.kind == "downshift":
                    self.downshifts_total += 1
                else:
                    self.upshifts_total += 1
        elif a.kind == "tier_floor":
            act.set_admission_tier_floor(
                None if a.value is None else int(a.value))
            with self._lock:
                self.tier_floor_changes_total += 1
                self.tier_floor = a.value
        elif a.kind == "flight":
            with self._lock:
                self.saturations_total += 1
            act.flight_trip(a.reason)

    # -- observability ---------------------------------------------------

    def signals(self) -> dict:
        """Flat counters for the owner's ``signals()`` export (prefixed
        ``control_`` there)."""
        with self._lock:
            out = {
                "actions_total": float(self.actions_total),
                "downshifts_total": float(self.downshifts_total),
                "upshifts_total": float(self.upshifts_total),
                "batch_resizes_total": float(self.batch_resizes_total),
                "tick_changes_total": float(self.tick_changes_total),
                "tier_floor_changes_total":
                    float(self.tier_floor_changes_total),
                "saturations_total": float(self.saturations_total),
                "rejected_quality_total": float(self.rejected_quality_total),
                "apply_errors_total": float(self.apply_errors_total),
            }
            if self.tier_floor is not None:
                out["tier_floor"] = float(self.tier_floor)
        return out

    def stats(self) -> dict:
        sig = self.signals()   # takes the lock itself — don't hold it
        with self._lock:
            return {
                **{k: int(v) for k, v in sig.items()
                   if k.endswith("_total")},
                "tier_floor": self.tier_floor,
                "tick_s": self.tick_s,
                "pending_applies": self._apply_q.qsize(),
                "decisions": list(self.decisions)[-32:],
            }
