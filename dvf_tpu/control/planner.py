"""Auto-plan plane — measured-profile plan search (ROADMAP item 3).

Every performance knob the runtime exposes — batch ladder, dispatch tick,
ingest/egress mode and depth, wire mode, codec thread split — was
hand-set until this module. The planner closes the loop the way a
measured-stage search does: for a given (op chain, geometry, device
topology) it

1. builds the full candidate grid (`candidate_grid`),
2. scores every candidate ANALYTICALLY from the compile-time
   calibration triple (``h2d_block_ms`` / ``d2h_block_ms`` /
   ``step_block_ms``) and any persisted stage-cost profile
   (`analytic_frame_ms`) — cheap arithmetic, no device time,
3. live-profiles only the analytic shortlist (≤ 1/3 of the grid, the
   acceptance bound) through the REAL frontend — each leg a short paced
   burst, ranked by `benchtools.ab_comparison`, the same leg machinery
   the bench table's A/B phase runs on (one paced-measurement path, not
   a third copy),
4. returns the winning :class:`Plan`, which the caller persists in the
   on-disk plan cache (`dvf_tpu.control.plan_cache`) so repeat startups
   skip the search entirely.

The chosen plan is not just applied once: `Plan.envelope()` hands the
PR 10/12 controllers their operating envelope — the batch ladder bounded
at the planned batch, the planned tick as the busy tick, the predicted
per-tick budget — so the reactive loops adapt WITHIN a measured plan
instead of around hard-coded defaults. `predicted_tick_cost_ms` is the
feed-forward half for admission: price an incoming tenant from its
signature's stage-cost profile before it runs, not after it hurts.

Determinism discipline: the planner itself is a pure function of its
inputs (grid, calibrations, profile, measurement results). All wall
clock lives in the caller's measurement runner and the ledger stamps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

from dvf_tpu.control.plan_cache import (
    PLANNER_VERSION,
    load_plan,
    save_plan,
    topology_fingerprint,
)

__all__ = [
    "PLANNER_VERSION",
    "Plan",
    "DEFAULT_PLAN",
    "candidate_grid",
    "analytic_frame_ms",
    "shortlist",
    "plan_search",
    "predicted_tick_cost_ms",
    "topology_fingerprint",
]

# Plan provenance: where did this plan's numbers come from?
PLAN_SOURCE_DEFAULT = "default"    # hand-set ServeConfig defaults
PLAN_SOURCE_ANALYTIC = "analytic"  # scored from calibrations, never run
PLAN_SOURCE_MEASURED = "measured"  # won a live paced-burst comparison
PLAN_SOURCE_CACHE = "cache"        # loaded from the on-disk plan cache

# Fraction of a small batch's device step that is fixed dispatch/launch
# overhead rather than per-frame compute — what makes a bigger batch
# worth anything in the analytic model. Deliberately coarse: the model
# only has to RANK candidates well enough that the live shortlist
# contains the true winner; the measurement decides.
_DISPATCH_FRAC = 0.35

# Streamed ingest overlaps H2D with compute up to this many slots deep;
# deeper queues only add latency, not throughput (mirrors the runtime's
# double-buffered staging).
_OVERLAP_CAP = 4.0


@dataclasses.dataclass(frozen=True)
class Plan:
    """One operating point for a serve frontend — every knob the search
    ranges over, plus provenance. Frozen: a plan is a value; applying
    it never mutates it."""

    batch_size: int = 8
    tick_s: float = 0.002
    ingest_depth: int = 4
    ingest: str = "streamed"
    egress: str = "streamed"
    wire: str = "raw"
    codec_threads: int = 4
    # Provenance (not part of the operating point):
    source: str = PLAN_SOURCE_DEFAULT
    predicted_frame_ms: Optional[float] = None
    measured_fps: Optional[float] = None
    searched: int = 0   # candidates live-profiled to pick this plan
    grid: int = 0       # full candidate-grid size they were drawn from

    def label(self) -> str:
        """Stable leg label for the A/B comparison and the ledger."""
        return (f"b{self.batch_size}"
                f"-t{self.tick_s * 1e3:g}ms"
                f"-d{self.ingest_depth}"
                f"-{self.ingest[:4]}/{self.egress[:4]}"
                f"-{self.wire}c{self.codec_threads}")

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_doc(cls, doc: Any) -> Optional["Plan"]:
        """A Plan from a cache/ledger dict, or None when the dict is not
        a plausible plan (corrupt cache entries degrade to a re-plan,
        never to a crash or a nonsense operating point)."""
        if not isinstance(doc, dict):
            return None
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in doc.items() if k in fields}
        try:
            plan = cls(**kw)
        except (TypeError, ValueError):
            return None
        if (not isinstance(plan.batch_size, int) or plan.batch_size < 1
                or not isinstance(plan.tick_s, (int, float))
                or not plan.tick_s > 0
                or not isinstance(plan.ingest_depth, int)
                or plan.ingest_depth < 1
                or plan.ingest not in ("streamed", "monolithic")
                or plan.egress not in ("streamed", "monolithic")
                or plan.wire not in ("raw", "jpeg", "delta")
                or not isinstance(plan.codec_threads, int)
                or plan.codec_threads < 1):
            return None
        return plan

    def envelope(self) -> dict:
        """The operating envelope handed to the reactive controllers:
        the PR 10 batch/tick loop adapts WITHIN these bounds (ladder
        capped at the planned batch, planned tick as the busy tick)
        instead of around hard-coded defaults. ``tick_budget_ms`` is
        the planner's predicted per-frame cost — advisory, for pricing
        and ledger context."""
        ladder = tuple(b for b in (1, 2, 4, 8, 16, 32, 64)
                       if b <= self.batch_size)
        if self.batch_size not in ladder:
            ladder = tuple(sorted(set(ladder) | {self.batch_size}))
        return {
            "batch_ladder": ladder,
            "batch_max": self.batch_size,
            "tick_busy_s": float(self.tick_s),
            "tick_budget_ms": self.predicted_frame_ms,
        }


DEFAULT_PLAN = Plan()


def candidate_grid(batch_cap: int = 32,
                   ticks: Sequence[float] = (0.001, 0.002, 0.005),
                   depths: Sequence[int] = (2, 4, 8),
                   modes: Sequence[Tuple[str, str]] = (
                       ("streamed", "streamed"),),
                   wires: Sequence[str] = ("raw",),
                   codec_threads: Sequence[int] = (4,)) -> List[Plan]:
    """The full candidate grid: batch ladder (doubling to ``batch_cap``)
    × tick interval × ingest depth × ingest/egress mode × wire mode ×
    codec thread split. The defaults collapse the wire/codec dimensions
    to the serve defaults — an in-process serve plan search gets no
    signal from them; a wire-bridge deployment passes its own axes."""
    batches = []
    b = 1
    while b <= max(1, int(batch_cap)):
        batches.append(b)
        b *= 2
    out = []
    for bs in batches:
        for tick in ticks:
            for depth in depths:
                for ingest, egress in modes:
                    for wire in wires:
                        for ct in codec_threads:
                            out.append(Plan(
                                batch_size=bs, tick_s=float(tick),
                                ingest_depth=int(depth), ingest=ingest,
                                egress=egress, wire=wire,
                                codec_threads=int(ct),
                                source=PLAN_SOURCE_ANALYTIC))
    return out


def analytic_frame_ms(plan: Plan, cal: Optional[dict],
                      cal_batch: int = 8,
                      stage_profile: Optional[dict] = None) -> float:
    """Predicted steady-state wall ms PER FRAME for one candidate, from
    the compile-time calibration triple (measured at ``cal_batch``) and
    optionally a persisted stage-cost profile.

    Model: a tick fires every ``max(tick interval, device work)`` and
    serves one batch. Device work = step (a fixed dispatch floor plus a
    batch-linear part) + transfers, with streamed ingest overlapping H2D
    behind compute up to the staging depth and streamed egress
    overlapping half the D2H. Coarse on purpose — it only has to RANK
    candidates so the live shortlist contains the true winner."""
    cal = cal or {}
    cal_batch = max(1, int(cal_batch))
    scale = plan.batch_size / float(cal_batch)

    step = cal.get("step_block_ms")
    if not isinstance(step, (int, float)) or not step > 0:
        # No calibration at all: fall back to the stage profile's
        # device component, else a 1 ms placeholder (ranking then
        # reduces to the tick/depth structure, which is still honest).
        step = _profile_mean_ms(stage_profile, "device",
                                default=1.0) * cal_batch
    step_ms = float(step) * (_DISPATCH_FRAC + (1.0 - _DISPATCH_FRAC) * scale)

    h2d = cal.get("h2d_block_ms")
    h2d = float(h2d) * scale if isinstance(h2d, (int, float)) else 0.0
    d2h = cal.get("d2h_block_ms")
    d2h = float(d2h) * scale if isinstance(d2h, (int, float)) else h2d
    if plan.ingest == "streamed":
        h2d /= max(1.0, min(float(plan.ingest_depth), _OVERLAP_CAP))
    if plan.egress == "streamed":
        d2h /= 2.0

    # Host-side codec cost rides on the egress path only when the wire
    # re-encodes; the thread split divides it.
    encode = 0.0
    if plan.wire in ("jpeg", "delta"):
        encode = (_profile_mean_ms(stage_profile, "encode", default=0.5)
                  * plan.batch_size / max(1, plan.codec_threads))

    work_ms = step_ms + h2d + d2h + encode
    tick_ms = plan.tick_s * 1e3
    return max(tick_ms, work_ms) / plan.batch_size


def _profile_mean_ms(stage_profile: Optional[dict], component: str,
                     default: float = 0.0) -> float:
    if isinstance(stage_profile, dict):
        row = (stage_profile.get("components_ms") or {}).get(component)
        if isinstance(row, dict) and isinstance(row.get("mean_ms"),
                                                (int, float)):
            return float(row["mean_ms"])
    return default


def shortlist(grid: Sequence[Plan], cal: Optional[dict],
              cal_batch: int = 8, stage_profile: Optional[dict] = None,
              live_budget: Optional[int] = None) -> List[Plan]:
    """The analytic prune: score the whole grid, keep the best ≤ 1/3
    for live profiling (the acceptance bound — a planner that profiles
    more than a third of the grid is not pruning). Candidates carry
    their predicted cost so the measured winner keeps both numbers.
    Deterministic: stable sort, ties broken by the plan's field order
    (smaller batch first — cheaper to be wrong about)."""
    grid = list(grid)
    limit = max(1, len(grid) // 3)
    budget = min(int(live_budget), limit) if live_budget else limit
    budget = max(1, budget)
    scored = [
        dataclasses.replace(
            p, predicted_frame_ms=round(
                analytic_frame_ms(p, cal, cal_batch, stage_profile), 4),
            source=PLAN_SOURCE_ANALYTIC)
        for p in grid
    ]
    scored.sort(key=lambda p: (
        p.predicted_frame_ms, p.batch_size, p.tick_s, p.ingest_depth))
    return scored[:budget]


def _load_ab_comparison() -> Callable:
    """The shared leg machinery lives in the repo-root ``benchtools``
    (jax-free, shared with benchmarks/run_table.py). The package may be
    imported without the repo root on sys.path — fall back to loading
    it by file, never by copying it."""
    try:
        from benchtools import ab_comparison
        return ab_comparison
    except ImportError:
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        spec = importlib.util.spec_from_file_location(
            "benchtools", os.path.join(root, "benchtools.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.ab_comparison


def plan_search(grid: Sequence[Plan],
                measure: Optional[Callable[[Plan], dict]] = None,
                *,
                cal: Optional[dict] = None,
                cal_batch: int = 8,
                stage_profile: Optional[dict] = None,
                live_budget: Optional[int] = None,
                log: Optional[Callable[[str], None]] = None
                ) -> Tuple[Plan, dict]:
    """The search: analytic prune to the shortlist, then live-profile
    each shortlisted candidate with ``measure(plan) ->
    {"fps": ...} | {"error": ...}`` (a short paced burst through the
    real frontend), ranked by the same `benchtools.ab_comparison` the
    bench table's A/B phase uses. Returns ``(winning Plan, comparison
    dict)`` — the comparison is what the caller ledgers (per-leg fps,
    winner, search cost).

    With no ``measure`` (or when every leg errors) the analytic best
    wins with ``source="analytic"`` — degraded but deterministic; the
    caller should NOT cache an analytic plan as if it were measured."""
    short = shortlist(grid, cal, cal_batch, stage_profile, live_budget)
    if measure is None:
        best = dataclasses.replace(short[0], searched=0, grid=len(grid))
        return best, {"winner": best.label(), "legs": 0,
                      "grid": len(grid), "analytic_only": True}

    by_label = {p.label(): p for p in short}
    ab = _load_ab_comparison()
    comp, _completed = ab(
        [(p.label(), p) for p in short],
        lambda _label, p: measure(p),
        log=log,
    )
    winner = comp.get("winner")
    if winner in by_label:
        leg = comp[winner]
        best = dataclasses.replace(
            by_label[winner],
            source=PLAN_SOURCE_MEASURED,
            measured_fps=float(leg["fps"]) if isinstance(
                leg.get("fps"), (int, float)) else None,
            searched=len(short), grid=len(grid))
    else:
        # Every live leg errored: the analytic front-runner, honestly
        # labeled, beats crashing the serve over an optimization.
        best = dataclasses.replace(
            short[0], source=PLAN_SOURCE_ANALYTIC,
            searched=len(short), grid=len(grid))
    comp["legs"] = len(short)
    comp["grid"] = len(grid)
    return best, comp


def predicted_tick_cost_ms(stage_profile: Optional[dict],
                           batch_size: int = 1) -> Optional[float]:
    """The feed-forward admission price: predicted per-tick device cost
    for a signature from its persisted stage-cost profile, BEFORE the
    tenant has run a single frame. Prefers the profile's measured
    ``tick_cost_ms`` EWMA; falls back to the per-frame device-path
    component means × batch. None when the profile has nothing usable —
    the caller admits reactively, exactly as before this plane."""
    if not isinstance(stage_profile, dict):
        return None
    t = stage_profile.get("tick_cost_ms")
    if isinstance(t, (int, float)) and t > 0:
        return float(t)
    per_frame = sum(
        _profile_mean_ms(stage_profile, c)
        for c in ("assemble_h2d", "device", "d2h"))
    if per_frame > 0:
        return per_frame * max(1, int(batch_size))
    return None


def plan_from_cache(cache_dir: Optional[str], signature: str, geometry,
                    topology: str) -> Optional[Plan]:
    """A cached plan for this exact key as a Plan (source re-stamped
    ``"cache"``), or None on any miss — the thin typed wrapper over
    `plan_cache.load_plan` that serve and fleet share."""
    doc = load_plan(cache_dir, signature, geometry, topology)
    plan = Plan.from_doc(doc)
    if plan is None:
        return None
    return dataclasses.replace(plan, source=PLAN_SOURCE_CACHE)


def plan_to_cache(cache_dir: Optional[str], signature: str, geometry,
                  topology: str, plan: Plan) -> Optional[str]:
    """Persist a MEASURED winner (analytic/default plans are never
    cached — a cache hit must mean "this was measured on this
    hardware", or warm restarts would trust a guess forever)."""
    if not cache_dir or plan.source != PLAN_SOURCE_MEASURED:
        return None
    return save_plan(cache_dir, signature, geometry, topology,
                     plan.to_doc())
