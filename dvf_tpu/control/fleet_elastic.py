"""Fleet elasticity: the controller that grows and shrinks the fleet.

PR 10's controllers bend a single replica's overload — downshift
quality, refuse low tiers at the door. What they cannot do is ADD
capacity: the tier admission floor *refuses* load the fleet could serve
by spawning one more replica, and scale-out is a human typing
``--replicas N``. :class:`FleetElasticityController` closes that outer
loop: a deterministic transducer over the fleet's merged telemetry rows
(`fleet.router.FleetFrontend.signals` composed with its
``elastic_view``) that emits ``scale_out`` / ``scale_in`` actions the
elastic plane (`fleet.elastic.ElasticFleetPlane`) applies through the
fleet's actuator seams — ``spawn_replica()`` (warm standby pool: the
spawn is a session-rebind, not a cold compile) and ``retire_replica()``
(PR 6's drain → migrate machinery, session affinity preserved).

Same discipline as `control.controllers`: ``step(row, prev)`` reads one
telemetry row, no wall-clock, no randomness — replaying a recorded
window through a fresh controller yields a byte-identical action list
(pinned in tests/test_elastic.py, and asserted by the committed
``ELASTIC_BENCH.json`` run), so a scale incident is reproducible from
its flight dump.

The decision inputs, in the order they matter:

- **admission-refusal rate** (``admission_refusals_total`` advancing):
  the leading indicator — the fleet is refusing sessions it could serve
  by growing, *before* any queue or percentile has moved;
- **per-replica occupancy** (bound sessions vs fleet session capacity):
  the second leading indicator — a fleet near its admission gates will
  start refusing next tick;
- **queue depth / shed / SLO-miss counters and fleet p99 vs SLO**: the
  lagging confirmation that the fleet is genuinely past capacity.

Scaling has TWO axes (ROADMAP item 2's last leg): *more replicas*
(another single-host replica — the default) and a *bigger replica*
(a ``MultiHostEngine`` process group: jax.distributed, one pjit program
across every host's devices — `fleet.multihost`). The controller picks
per the measured signature cost profiles (PR 11, ``--profile-dir``):
when the dominant signature's measured device-stage cost alone exceeds
``bigger_replica_device_ms``, adding small replicas multiplies queueing
without ever bringing one frame's device time down — only a replica
with more devices can — so the scale-out action targets the
``multihost`` flavor; otherwise more (cheap, independently
schedulable) single-host replicas win. The profiling-driven
adaptive-partition discipline of arXiv:2605.25682, applied to the
fleet's outermost knob.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from dvf_tpu.control.controllers import Action

# Replica flavors a scale-out action may target (``Action.target``).
FLAVOR_DEFAULT = "default"      # whatever FleetConfig.mode spawns
FLAVOR_MULTIHOST = "multihost"  # MultiHostEngine process group
FLAVOR_RELAY = "relay"          # broadcast egress relay (no filter
#   compute — a RelayNode fanning an already-encoded tier out to its
#   own subscribers; the THIRD scaling axis, broadcast plane)


@dataclasses.dataclass
class ElasticConfig:
    """Knobs for the fleet elasticity loop (CLI: ``--autoscale``)."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 0.25       # fleet telemetry cadence the elastic
    #   plane arms the ring at (when nothing armed it already)
    # -- pressure predicate ----------------------------------------------
    sessions_high_frac: float = 0.85   # bound sessions / fleet session
    #   capacity beyond which the fleet reads as near-saturated (the
    #   tier guard refuses batch tier at the same watermark: growing
    #   HERE is what turns that refusal back into served load)
    queue_high_per_session: float = 3.0  # standing fleet queue_depth per
    #   open session that reads as overload (PR 10's predicate, one
    #   tier up)
    # -- scale-out -------------------------------------------------------
    out_after: int = 2             # consecutive pressured samples before
    #   a scale-out (short on purpose: refusals are the leading signal
    #   and every refused open is load the fleet turned away)
    out_cooldown: int = 6          # min samples between scale-outs — one
    #   spawn must be observable in the window before the next is judged
    # -- scale-in --------------------------------------------------------
    in_after: int = 24             # consecutive calm samples before a
    #   scale-in (long: a retire costs migrations, and the burst that
    #   scaled us out tends to come back — soak posture, PR 10's)
    in_cooldown: int = 8
    in_occupancy_frac: float = 0.6  # a retire must leave the SURVIVORS
    #   at most this occupied (projected bound-sessions / post-retire
    #   capacity) — never shrink into immediate re-pressure, the
    #   admission limit cycle one tier up
    # -- two-axis choice -------------------------------------------------
    bigger_replica_device_ms: float = 0.0  # 0 disables the multihost
    #   axis. >0: when the dominant signature's measured per-tick device
    #   cost (stage profiles, PR 11) exceeds this, scale-out targets the
    #   multihost flavor — more single-host replicas cannot shrink ONE
    #   frame's device time, only more devices under one program can
    # -- saturation ------------------------------------------------------
    saturate_after: int = 10       # pressured samples at max_replicas
    #   with nothing left to spawn → flight dump (one per episode)
    # -- relay axis (broadcast fan-out) ----------------------------------
    relay_subscribers_high: int = 0  # 0 disables the relay axis (the
    #   default: recorded pre-broadcast replay windows stay byte-
    #   identical, and a fleet that never publishes has nothing to
    #   relay). >0: once direct subscribers per egress point (origin +
    #   live relays) reach this, fan-out — not filter compute — is the
    #   bottleneck, and the right spawn is a relay-only egress replica,
    #   never another filter replica
    relay_out_after: int = 2       # consecutive fan-out-pressured
    #   samples before a relay spawn (short, like out_after: every
    #   sample over the watermark is subscriber-visible egress drop)
    relay_in_after: int = 24       # consecutive fan-out-calm samples
    #   before a relay retire (soak posture, like in_after)
    relay_cooldown: int = 6        # min samples between relay actions
    max_relays: int = 4            # relay-replica ceiling
    # -- feed-forward (predictive) axis -----------------------------------
    predictive: bool = False       # step the fleet with
    #   PredictiveElasticityController: project queue/occupancy growth
    #   from the telemetry window's slope and spawn BEFORE the reactive
    #   predicate fires (auto-plan plane; CLI --autoplan arms it)
    predict_slope_window: int = 3  # rows the slope is fit over (first
    #   vs last — robust to one noisy sample, still just arithmetic)
    predict_horizon: int = 4       # samples ahead the projection looks:
    #   roughly the spawn lead time (standby rebind + first window) in
    #   ring samples, so capacity lands when the projection said the
    #   watermark would be crossed


def fleet_pressure(row: dict, prev: Optional[dict],
                   config: ElasticConfig) -> Optional[str]:
    """THE fleet-tier overload predicate, stated once. Returns the
    triggering reason (a human-readable tag for the decision log), or
    None when calm. Counter inputs compare against ``prev`` so a burst
    shows as *advancing* refusals/sheds, not as a latched lifetime
    total."""
    def advancing(key: str) -> bool:
        if prev is None:
            return False
        cur_v, prev_v = row.get(key), prev.get(key)
        return (cur_v is not None and prev_v is not None
                and float(cur_v) > float(prev_v))

    if advancing("admission_refusals_total"):
        return "admission refusals advancing"
    cap = float(row.get("capacity_sessions") or 0.0)
    bound = float(row.get("bound_sessions") or 0.0)
    if cap > 0 and bound >= config.sessions_high_frac * cap:
        return (f"occupancy {bound:g}/{cap:g} >= "
                f"{config.sessions_high_frac:g}")
    open_sessions = max(1.0, float(row.get("open_sessions") or 0.0))
    qd = float(row.get("fleet_queue_depth") or 0.0)
    if qd >= config.queue_high_per_session * open_sessions:
        return f"standing queue {qd:g} over {open_sessions:g} sessions"
    if advancing("fleet_shed_total"):
        return "sheds advancing"
    if advancing("fleet_slo_miss_total"):
        return "SLO misses advancing"
    p99 = row.get("fleet_p99_ms")
    slo = row.get("slo_ms")
    if p99 is not None and slo is not None and float(p99) > float(slo):
        # Worst replica's p99 over the SLO: lagging, but decisive —
        # WHEN the miss counter cannot arbitrate. With counters
        # present, advancing misses already returned above and a
        # non-advancing window means the overload ENDED (the PR 10
        # lesson: lifetime percentiles latch long after a burst), so
        # p99 alone must not re-latch pressure; it decides only on the
        # first sample or when the row carries no miss counter.
        if prev is None or row.get("fleet_slo_miss_total") is None:
            return f"fleet p99 {float(p99):.0f}ms > SLO {float(slo):.0f}ms"
    return None


def relay_pressure(row: dict, prev: Optional[dict],
                   config: ElasticConfig) -> Optional[str]:
    """The fan-out overload predicate — the broadcast analogue of
    :func:`fleet_pressure`, stated once. Fan-out pressure is NOT filter
    pressure: every queue/p99/refusal signal above can be calm while
    tens of thousands of subscribers drain one origin's egress, so the
    relay axis reads only the broadcast row — subscribers per egress
    point (origin + live relays) against the watermark, and advancing
    egress drops as the lagging confirmation."""
    if config.relay_subscribers_high <= 0:
        return None
    subs = float(row.get("broadcast_subscribers") or 0.0)
    if subs <= 0:
        return None
    egress = 1.0 + float(row.get("relays_live") or 0.0)
    if subs / egress >= config.relay_subscribers_high:
        return (f"fan-out {subs:g} subscribers over {egress:g} egress "
                f"point(s) >= {config.relay_subscribers_high}/point")
    if prev is not None:
        cur_v = row.get("broadcast_dropped_total")
        prev_v = prev.get("broadcast_dropped_total")
        if (cur_v is not None and prev_v is not None
                and float(cur_v) > float(prev_v)):
            return "broadcast egress drops advancing"
    return None


class FleetElasticityController:
    """Deterministic scale-out/scale-in transducer (module docstring).

    ``step(row, prev)`` expects the composed fleet control row: the
    flat ring sample plus ``FleetFrontend.elastic_view()`` —
    ``replicas_live``/``replicas_desired``/``replicas_max_flavor``
    gauges, ``replica_rows`` (per-replica ``{rid, sessions,
    queue_depth}``), capacity, and the startup-loaded signature cost
    profile. Emits at most one scale action per step: elasticity is a
    slow loop by design (every action is observable in the window
    before the next is judged)."""

    def __init__(self, config: Optional[ElasticConfig] = None):
        self.config = config or ElasticConfig()
        if self.config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.config.in_occupancy_frac >= self.config.sessions_high_frac:
            # A retire that leaves the survivors above the scale-OUT
            # occupancy watermark re-trips pressure on the next sample:
            # scale-in → scale-out → scale-in, every leg paying a spawn
            # or a drain+migration. Refuse the config rather than run
            # the limit cycle.
            raise ValueError(
                f"in_occupancy_frac ({self.config.in_occupancy_frac}) "
                f"must be < sessions_high_frac "
                f"({self.config.sessions_high_frac}): a shrink must not "
                f"land the survivors straight back at the scale-out "
                f"watermark")
        self._i = 0
        self._pressure_streak = 0
        self._calm_streak = 0
        self._cooldown = 0
        self._saturation_open = False
        # Relay axis: independent streaks/cooldown — fan-out pressure
        # and filter pressure are different bottlenecks and must never
        # share a hysteresis state (a compute burst would reset the
        # relay calm clock and pin surplus relays alive).
        self._relay_pressure_streak = 0
        self._relay_calm_streak = 0
        self._relay_cooldown = 0

    # -- the decision step ------------------------------------------------

    def step(self, row: dict, prev: Optional[dict]) -> List[Action]:
        cfg = self.config
        self._i += 1
        if self._cooldown > 0:
            self._cooldown -= 1
        reason = self._pressure(row, prev)
        if reason is not None:
            self._pressure_streak += 1
            self._calm_streak = 0
        else:
            self._calm_streak += 1
            self._pressure_streak = 0
        desired = int(row.get("replicas_desired") or 0)
        out: List[Action] = []
        if reason is not None and self._pressure_streak >= cfg.out_after:
            if desired < cfg.max_replicas and self._cooldown <= 0:
                flavor = self._flavor(row)
                out.append(Action(
                    "scale_out", flavor, desired + 1,
                    f"{reason} (pressure x{self._pressure_streak}), "
                    f"replicas {desired} -> {desired + 1}"))
                self._cooldown = cfg.out_cooldown
                self._saturation_open = False
            elif desired >= cfg.max_replicas:
                # Nothing left to spawn while pressure holds: the
                # saturation signal the plane turns into a flight dump
                # (one per episode — "the fleet gave everything").
                if (self._pressure_streak >= cfg.saturate_after
                        and not self._saturation_open):
                    self._saturation_open = True
                    out.append(Action(
                        "flight", None, None,
                        f"fleet saturated: {reason} with every replica "
                        f"spawned ({desired}/{cfg.max_replicas}), "
                        f"pressure sustained x{self._pressure_streak}"))
        elif reason is None:
            self._saturation_open = False
            if (self._calm_streak >= cfg.in_after
                    and desired > cfg.min_replicas
                    and self._cooldown <= 0):
                victim = self._victim(row, desired)
                if victim is not None:
                    out.append(Action(
                        "scale_in", victim, desired - 1,
                        f"calm x{self._calm_streak}, replicas "
                        f"{desired} -> {desired - 1} (retiring {victim})"))
                    self._cooldown = cfg.in_cooldown
                    # Each further step down is judged on fresh calm:
                    # releasing the whole surplus at once would dump
                    # every retiring replica's migrations into one
                    # window.
                    self._calm_streak = 0
        out.extend(self._relay_step(row, prev))
        return out

    def _pressure(self, row: dict, prev: Optional[dict]) -> Optional[str]:
        """The pressure-predicate seam. The base controller is purely
        reactive (`fleet_pressure`); the predictive subclass widens this
        to ALSO read projected pressure — everything downstream
        (streaks, cooldowns, flavor choice, victim selection) is shared,
        so the two controllers differ ONLY in when pressure is first
        seen."""
        return fleet_pressure(row, prev, self.config)

    def _relay_step(self, row: dict, prev: Optional[dict]) -> List[Action]:
        """The relay axis, stepped on the same row (at most one relay
        action per step, independent of any scale action the same
        step emitted — they move different resources)."""
        cfg = self.config
        if cfg.relay_subscribers_high <= 0:
            return []
        if self._relay_cooldown > 0:
            self._relay_cooldown -= 1
        reason = relay_pressure(row, prev, cfg)
        relays = int(float(row.get("relays_live") or 0.0))
        out: List[Action] = []
        if reason is not None:
            self._relay_pressure_streak += 1
            self._relay_calm_streak = 0
            if (self._relay_pressure_streak >= cfg.relay_out_after
                    and relays < cfg.max_relays
                    and self._relay_cooldown <= 0):
                out.append(Action(
                    "relay_out", FLAVOR_RELAY, relays + 1,
                    f"{reason} (pressure x{self._relay_pressure_streak}), "
                    f"relays {relays} -> {relays + 1}"))
                self._relay_cooldown = cfg.relay_cooldown
        else:
            self._relay_calm_streak += 1
            self._relay_pressure_streak = 0
            if (self._relay_calm_streak >= cfg.relay_in_after
                    and relays > 0 and self._relay_cooldown <= 0):
                out.append(Action(
                    "relay_in", None, relays - 1,
                    f"broadcast calm x{self._relay_calm_streak}, "
                    f"relays {relays} -> {relays - 1}"))
                self._relay_cooldown = cfg.relay_cooldown
                # Fresh calm per further step down (scale-in's rule).
                self._relay_calm_streak = 0
        return out

    # -- helpers ----------------------------------------------------------

    def _flavor(self, row: dict) -> str:
        """More-replicas vs bigger-replica (module docstring): the
        multihost flavor only when it is configured, available
        (``multihost_available`` — the fleet knows a signature to pin
        the group to), and the measured device cost says one host is
        the bottleneck."""
        cfg = self.config
        if cfg.bigger_replica_device_ms <= 0:
            return FLAVOR_DEFAULT
        if not row.get("multihost_available"):
            return FLAVOR_DEFAULT
        device_ms = row.get("profile_device_ms")
        if device_ms is None:
            return FLAVOR_DEFAULT
        if float(device_ms) > cfg.bigger_replica_device_ms:
            return FLAVOR_MULTIHOST
        return FLAVOR_DEFAULT

    def _victim(self, row: dict, desired: int) -> Optional[str]:
        """Deterministic scale-in victim: the least-loaded replica
        (fewest bound sessions, queue depth then id breaking ties —
        fewest migrations when it drains), and only when the survivors
        can absorb the whole bound-session load below
        ``in_occupancy_frac`` — a shrink must never re-create the
        pressure it took ``in_after`` calm samples to rule out."""
        cfg = self.config
        rows = [r for r in (row.get("replica_rows") or ())
                if isinstance(r, dict) and r.get("rid") is not None]
        if len(rows) < 2:
            return None
        per_replica_cap = float(row.get("capacity_sessions") or 0.0) / max(
            1, int(row.get("replicas_live") or desired))
        if per_replica_cap <= 0:
            return None
        bound = float(row.get("bound_sessions") or 0.0)
        survivors_cap = per_replica_cap * (desired - 1)
        if survivors_cap <= 0 or bound > cfg.in_occupancy_frac * survivors_cap:
            return None
        return min(
            rows,
            key=lambda r: (float(r.get("sessions") or 0.0),
                           float(r.get("queue_depth") or 0.0),
                           str(r.get("rid"))),
        )["rid"]


class PredictiveElasticityController(FleetElasticityController):
    """Feed-forward elasticity (auto-plan plane, PR 20): project where
    the fleet is GOING from the telemetry window's slope and read
    pressure before the reactive predicate fires — a standby rebind
    takes samples to land, and a spawn triggered by advancing refusals
    has, by definition, already turned sessions away.

    Two projections, both plain first-vs-last slopes over
    ``predict_slope_window`` rows extrapolated ``predict_horizon``
    samples ahead, judged against the SAME watermarks the reactive
    predicate uses:

    - **occupancy**: projected bound sessions crossing
      ``sessions_high_frac`` × capacity — the refusal precursor (a
      fleet saturates its session slots, then refuses);
    - **queue depth**: projected standing queue crossing
      ``queue_high_per_session`` × open sessions — the latency
      precursor.

    Either projection only counts once the CURRENT value is at least
    halfway to its watermark: a slope fit near zero load (one tenant
    opening on an idle fleet) extrapolates to anything, and a spawn
    it triggers is noise, not feed-forward — prediction accelerates a
    trend already approaching the watermark, it does not invent one.

    The reactive predicate still runs first and wins when it fires
    (measured overload is ground truth; prediction only ADDS pressure,
    never masks it), so the predictive controller is a strict widening:
    every window the reactive controller scales on, this one does too,
    no later. Same determinism discipline as the base class — the
    slope history is rebuilt from the rows alone, no wall clock, so a
    recorded window replays byte-identically (pinned by
    tests/test_planner.py and the committed PLAN_BENCH.json)."""

    def __init__(self, config: Optional[ElasticConfig] = None):
        super().__init__(config)
        if self.config.predict_slope_window < 2:
            raise ValueError("predict_slope_window must be >= 2")
        if self.config.predict_horizon < 1:
            raise ValueError("predict_horizon must be >= 1")
        # (queue_depth, bound_sessions) per step, bounded at the slope
        # window — state derived from rows only (replay determinism).
        self._history: List[tuple] = []

    def _pressure(self, row: dict, prev: Optional[dict]) -> Optional[str]:
        cfg = self.config
        qd = float(row.get("fleet_queue_depth") or 0.0)
        bound = float(row.get("bound_sessions") or 0.0)
        self._history.append((qd, bound))
        if len(self._history) > cfg.predict_slope_window:
            self._history.pop(0)
        reactive = fleet_pressure(row, prev, cfg)
        if reactive is not None:
            return reactive
        if len(self._history) < cfg.predict_slope_window:
            return None
        n = len(self._history) - 1
        q_slope = (self._history[-1][0] - self._history[0][0]) / n
        b_slope = (self._history[-1][1] - self._history[0][1]) / n
        cap = float(row.get("capacity_sessions") or 0.0)
        if b_slope > 0 and cap > 0:
            high = cfg.sessions_high_frac * cap
            proj_bound = bound + b_slope * cfg.predict_horizon
            if proj_bound >= high and bound >= 0.5 * high:
                return (f"projected occupancy {proj_bound:g}/{cap:g} in "
                        f"{cfg.predict_horizon} samples (slope "
                        f"{b_slope:+g}/sample) >= "
                        f"{cfg.sessions_high_frac:g}")
        if q_slope > 0:
            open_sessions = max(1.0, float(row.get("open_sessions") or 0.0))
            q_high = cfg.queue_high_per_session * open_sessions
            proj_q = qd + q_slope * cfg.predict_horizon
            if proj_q >= q_high and qd >= 0.5 * q_high:
                return (f"projected queue {proj_q:g} in "
                        f"{cfg.predict_horizon} samples (slope "
                        f"{q_slope:+g}/sample) over {open_sessions:g} "
                        f"sessions")
        return None


def make_elasticity_controller(
        config: Optional[ElasticConfig] = None) -> FleetElasticityController:
    """The one construction seam: predictive when the config says so
    (``--autoplan`` arms it at the fleet tier), reactive otherwise —
    so the elastic plane, the bench harness, and the replay tests can
    never disagree about which controller a config builds."""
    config = config or ElasticConfig()
    if config.predictive:
        return PredictiveElasticityController(config)
    return FleetElasticityController(config)
