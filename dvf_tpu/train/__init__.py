"""Training subsystem (style-transfer perceptual + SR self-supervised).

The reference is inference-only; training exists here because the flagship
neural filter (style transfer, BASELINE.json configs[4]) needs trained
weights. The train step is a single pjit-compiled program over the framework
mesh: batch data-parallel over ``data``, params tensor-parallel over
``model``, activations optionally spatially sharded over ``space``.
"""

from dvf_tpu.train.style import (  # noqa: F401
    StyleTrainConfig,
    TrainState,
    init_train_state,
    make_train_step,
    style_loss_fn,
)
from dvf_tpu.train.sr import (  # noqa: F401
    SrTrainConfig,
    SrTrainState,
)
