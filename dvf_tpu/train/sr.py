"""Self-supervised training for the ESPCN super-resolution net.

No external dataset needed (zero-egress environment, and the reference
ships none): the video stream itself supervises — each HR frame is
area-downscaled ×r on device to make the LR input, and the net learns to
reconstruct the original. Loss is Charbonnier (smooth L1), the standard
SR choice: L2 over-penalizes outliers and trains blurry nets.

Sharding mirrors train.style exactly — ONE all-manual ``jax.shard_map``
over the mesh: batch folded over ('data', 'space'), Megatron TP over
'model' with the single psum inside the forward
(models.espcn.tp_inner_apply), grads pmean'd over the data axes, adam on
locally-owned slices. See train.style.make_train_step for the rationale
(incl. the XLA bugs ruling out GSPMD-auto here).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dvf_tpu.utils.compat import shard_map

from dvf_tpu.models.espcn import (
    EspcnConfig,
    apply_espcn,
    init_espcn,
    param_pspecs,
    tp_inner_apply,
)


@dataclasses.dataclass(frozen=True)
class SrTrainConfig:
    net: EspcnConfig = EspcnConfig()
    learning_rate: float = 1e-3
    charbonnier_eps: float = 1e-3


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SrTrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def synthesize_structured_batch(rng: "np.random.Generator", batch: int,
                                size: int) -> "np.ndarray":
    """Randomized structured HR frames for self-supervised SR training.

    Each frame draws fresh grating frequencies/orientations, ring centers,
    and checker scales — a *distribution* of edge-rich content, so the net
    must learn edge reconstruction instead of memorizing a fixed frame
    cycle (training on SyntheticSource's 16-frame round-robin overfits:
    measured −0.2 dB vs nearest on unseen frames, vs several dB gained
    when trained on this generator). Values uint8, shape (B, size, size, 3).
    """
    import numpy as np

    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    out = np.empty((batch, size, size, 3), np.uint8)
    for b in range(batch):
        chans = []
        for _ in range(3):
            kind = rng.integers(0, 3)
            if kind == 0:  # oriented grating
                freq = rng.uniform(6.0, 32.0)
                ang = rng.uniform(0.0, np.pi)
                ph = rng.uniform(0.0, 2 * np.pi)
                u = xx * np.cos(ang) + yy * np.sin(ang)
                ch = 127.5 + 127.5 * np.sin(2 * np.pi * u / freq + ph)
            elif kind == 1:  # rings around a random center
                cy, cx = rng.uniform(0, size, 2)
                rad = np.hypot(yy - cy, xx - cx)
                ch = 127.5 + 127.5 * np.sin(rad / rng.uniform(2.0, 8.0))
            else:  # hard-edged checker, random scale + offset
                s = rng.integers(5, 21)
                oy, ox = rng.integers(0, s, 2)
                ch = (((xx + ox) // s).astype(int)
                      + ((yy + oy) // s).astype(int)) % 2 * 255.0
            chans.append(ch)
        out[b] = np.clip(np.stack(chans, -1), 0, 255).astype(np.uint8)
    return out


def downscale_area(x: jnp.ndarray, r: int) -> jnp.ndarray:
    """Area (box) ×r downscale — the supervision pair generator. A pure
    reshape+mean, so it fuses into the train step; H and W must be
    divisible by r (the train loop crops to guarantee it)."""
    b, h, w, c = x.shape
    if h % r or w % r:
        raise ValueError(f"({h}, {w}) not divisible by scale {r}")
    xf = x.astype(jnp.float32)
    return xf.reshape(b, h // r, r, w // r, r, c).mean(axis=(2, 4)).astype(x.dtype)


def sr_loss_fn(
    params: Any,
    hr_batch: jnp.ndarray,
    config: SrTrainConfig,
    apply_fn=None,
) -> Tuple[jnp.ndarray, dict]:
    """``apply_fn`` defaults to the single-shard forward; make_train_step
    passes the per-shard TP version (called inside shard_map)."""
    apply_fn = apply_fn or (lambda p, b: apply_espcn(p, b, config.net))
    lr_batch = downscale_area(hr_batch, config.net.scale)
    out = apply_fn(params, lr_batch)
    diff = out.astype(jnp.float32) - hr_batch.astype(jnp.float32)
    loss = jnp.mean(jnp.sqrt(diff * diff + config.charbonnier_eps**2))
    # MSE (not PSNR) goes in the metrics: under data parallelism metrics
    # are pmean'd across shards, and mean-of-MSEs is the global MSE
    # (equal shard sizes) while mean-of-PSNRs is Jensen-biased high. The
    # train step derives PSNR once, after the pmean.
    mse = jnp.mean(diff * diff)
    return loss, {"loss": loss, "mse": mse}


def make_optimizer(config: SrTrainConfig) -> optax.GradientTransformation:
    return optax.adam(config.learning_rate)


def init_train_state(rng: jax.Array, config: SrTrainConfig = SrTrainConfig()) -> SrTrainState:
    params = init_espcn(rng, config.net)
    return SrTrainState(
        params=params,
        opt_state=make_optimizer(config).init(params),
        step=jnp.zeros((), jnp.int32),
    )


def state_pspecs(state: SrTrainState, config: SrTrainConfig) -> SrTrainState:
    """Spec tree mirroring an SrTrainState; adam moments inherit each
    param leaf's TP spec (same path-resolution rule as train.style)."""
    p_specs = param_pspecs(config.net)

    def opt_spec(path, _leaf):
        keys = [k.key for k in path if isinstance(k, jax.tree_util.DictKey)]
        node: Any = p_specs
        for k in keys:
            if not isinstance(node, dict) or k not in node:
                return P()
            node = node[k]
        return node if isinstance(node, P) else P()

    return SrTrainState(
        params=p_specs,
        opt_state=jax.tree_util.tree_map_with_path(opt_spec, state.opt_state),
        step=P(),
    )


def shard_train_state(state: SrTrainState, mesh: Mesh, config: SrTrainConfig) -> SrTrainState:
    specs = state_pspecs(state, config)
    put = lambda x, s: jax.device_put(x, NamedSharding(mesh, s))  # noqa: E731
    return SrTrainState(
        params=jax.tree.map(put, state.params, specs.params),
        opt_state=jax.tree.map(put, state.opt_state, specs.opt_state),
        step=put(state.step, specs.step),
    )


def train_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(("data", "space")))


def make_train_step(
    mesh: Mesh,
    config: SrTrainConfig = SrTrainConfig(),
    state_template: SrTrainState = None,
    donate: bool = True,
) -> Callable[[SrTrainState, jnp.ndarray], Tuple[SrTrainState, dict]]:
    """Jitted mesh-sharded step: ``(state, hr_batch) -> (state, metrics)``
    with hr_batch sharded per :func:`train_batch_sharding`."""
    if state_template is None:
        raise ValueError("make_train_step needs a state_template SrTrainState")
    optimizer = make_optimizer(config)
    apply_fn = tp_inner_apply(config.net)
    specs = state_pspecs(state_template, config)
    dp_axes = ("data", "space")

    def local_step(state: SrTrainState, batch: jnp.ndarray):
        grads, metrics = jax.grad(sr_loss_fn, has_aux=True)(
            state.params, batch, config, apply_fn,
        )
        grads = lax.pmean(grads, dp_axes)
        metrics = lax.pmean(metrics, dp_axes)
        metrics["psnr"] = -10.0 * jnp.log10(metrics.pop("mse") + 1e-12)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        return (
            SrTrainState(
                params=optax.apply_updates(state.params, updates),
                opt_state=opt_state,
                step=state.step + 1,
            ),
            metrics,
        )

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(specs, P(dp_axes)),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
