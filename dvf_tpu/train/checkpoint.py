"""Checkpoint / resume for style-transfer training (orbax-backed).

The reference has nothing persistent (SURVEY.md §5.4 — its pipeline is
stateless per frame); the framework's training loop does: net params, adam
moments, frozen VGG weights, target Grams, step counter. Orbax writes the
whole TrainState pytree; restore takes the abstract template (from
``init_train_state``) so dtypes/shapes — and on restore-onto-a-mesh, the
shardings — come back exactly.

Checkpoints are standard orbax directories: resumable across processes and
readable by any orbax tool.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from dvf_tpu.train.style import StyleTrainConfig, TrainState, shard_train_state


def save_checkpoint(path: str, state: TrainState) -> str:
    """Write ``state`` to ``path`` (an empty/new directory). Blocking."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-writing "final" (or a colliding step dir) on a
        # resumed run must overwrite, not crash the end of training.
        ckptr.save(path, jax.device_get(state), force=True)
    return path


def load_params(path: str):
    """Restore ONLY the net params from a train checkpoint — the inference
    loader (serve --style-checkpoint): no optimizer/VGG/gram state, no
    TrainState template, no mesh required. Returns the param pytree ready
    to pass to ``get_filter("style_transfer", params=...)``."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path)
    if hasattr(restored, "params"):
        return restored.params
    return restored["params"]


def load_style_filter(ckpt_dir: str):
    """Rebuild the style_transfer Filter from a train checkpoint directory
    (the single loader behind ``serve --style-checkpoint`` and the tests).

    Requires the sidecar ``config.json`` the train CLI writes: guessing
    default architecture on a mismatch would silently skip trained layers
    (extra residual blocks never run) or crash with an opaque shape error.
    """
    import json

    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"style checkpoint dir {ckpt_dir!r} does not exist")
    # Prefer 'final'; fall back to the newest step_* checkpoint — a run
    # killed mid-training leaves step dirs but no final, and those must
    # stay loadable (the sidecar is written before training starts).
    final = os.path.join(ckpt_dir, "final")
    if not os.path.isdir(final):
        steps = sorted(d for d in os.listdir(ckpt_dir) if d.startswith("step_"))
        if not steps:
            raise FileNotFoundError(
                f"{ckpt_dir!r} has no 'final' or step_* checkpoint — pass "
                f"the directory given to train --checkpoint-dir")
        final = os.path.join(ckpt_dir, steps[-1])
    cfg_path = os.path.join(ckpt_dir, "config.json")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"{cfg_path} missing — the net architecture cannot be recovered "
            f"(the train CLI writes this sidecar at training start)")
    try:
        with open(cfg_path) as f:
            sc = json.load(f)
        base_channels, n_residual = sc["base_channels"], sc["n_residual"]
    except (json.JSONDecodeError, KeyError) as e:
        raise ValueError(
            f"{cfg_path} is corrupt or missing required keys "
            f"(base_channels, n_residual): {e}") from e

    from dvf_tpu.ops import get_filter

    return get_filter(
        "style_transfer",
        params=load_params(final),
        base_channels=base_channels,
        n_residual=n_residual,
    )


def restore_checkpoint(
    path: str,
    template: TrainState,
    mesh=None,
    config: Optional[StyleTrainConfig] = None,
) -> TrainState:
    """Load a TrainState from ``path``.

    ``template`` (e.g. a fresh ``init_train_state``) supplies the pytree
    structure. With ``mesh`` + ``config`` the restored state is placed
    straight onto the mesh per ``state_pspecs`` (resume-on-slice).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=jax.device_get(template))
    state = TrainState(**{
        f: getattr(restored, f) if hasattr(restored, f) else restored[f]
        for f in ("params", "opt_state", "vgg_params", "style_grams", "step")
    }) if not isinstance(restored, TrainState) else restored
    if mesh is not None:
        state = shard_train_state(state, mesh, config or StyleTrainConfig())
    return state
