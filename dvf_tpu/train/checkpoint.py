"""Checkpoint / resume for the training families (orbax-backed).

The reference has nothing persistent (SURVEY.md §5.4 — its pipeline is
stateless per frame); the framework's training loops do: net params, adam
moments, (for style) frozen VGG weights and target Grams, step counter.
Orbax writes the whole TrainState pytree; restore takes the abstract
template (from ``init_train_state``) so dtypes/shapes — and on
restore-onto-a-mesh, the shardings — come back exactly.

Checkpoints are standard orbax directories: resumable across processes and
readable by any orbax tool. Both families share one directory layout
('final' preferred, newest 'step_*' fallback, 'config.json' architecture
sidecar) via the `_resolve_*` helpers, so layout fixes land once.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax

from dvf_tpu.train.style import StyleTrainConfig, TrainState, shard_train_state


def save_checkpoint(path: str, state) -> str:
    """Write a TrainState pytree (either family) to ``path``. Blocking."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-writing "final" (or a colliding step dir) on a
        # resumed run must overwrite, not crash the end of training.
        ckptr.save(path, jax.device_get(state), force=True)
    return path


def load_params(path: str):
    """Restore ONLY the net params from a train checkpoint — the inference
    loaders: no optimizer/VGG/gram state, no TrainState template, no mesh
    required. Returns the param pytree ready for ``get_filter(...,
    params=...)``."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path)
    if hasattr(restored, "params"):
        return restored.params
    return restored["params"]


# ------------------------------------------------- shared layout helpers

def resolve_checkpoint_dir(ckpt_dir: str, family: str, train_cmd: str) -> str:
    """Map a train --checkpoint-dir to the concrete checkpoint to load:
    prefer 'final'; fall back to the newest step_* — a run killed
    mid-training leaves step dirs but no final, and those must stay
    loadable (the sidecar is written before training starts)."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"{family} checkpoint dir {ckpt_dir!r} does not exist")
    final = os.path.join(ckpt_dir, "final")
    if not os.path.isdir(final):
        import re

        # step_NNNNNN only: orbax stages async writes in
        # 'step_*.orbax-checkpoint-tmp' dirs that sort AFTER every
        # committed step — a run killed mid-(async)-write must fall back
        # to the newest COMMITTED checkpoint, never the torn tmp dir.
        # Numeric sort: lexicographic order would rely on the CLI's 6-digit
        # zero padding and mis-rank step_1000000 below step_999999 (or any
        # externally written unpadded dir).
        steps = sorted((d for d in os.listdir(ckpt_dir)
                        if re.fullmatch(r"step_\d+", d)),
                       key=lambda d: int(d[len("step_"):]))
        if not steps:
            raise FileNotFoundError(
                f"{ckpt_dir!r} has no 'final' or step_* checkpoint — pass "
                f"the directory given to {train_cmd} --checkpoint-dir")
        final = os.path.join(ckpt_dir, steps[-1])
    return final


def _read_sidecar(ckpt_dir: str, required: Sequence[str]) -> dict:
    """Load the config.json architecture sidecar the train CLIs write.
    Required: guessing default architecture on a mismatch would silently
    skip trained layers or crash with an opaque shape error."""
    import json

    cfg_path = os.path.join(os.path.abspath(ckpt_dir), "config.json")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"{cfg_path} missing — the net architecture cannot be recovered "
            f"(the train CLI writes this sidecar at training start)")
    try:
        with open(cfg_path) as f:
            sc = json.load(f)
        missing = [k for k in required if k not in sc]
        if missing:
            raise KeyError(", ".join(missing))
    except (json.JSONDecodeError, KeyError) as e:
        raise ValueError(
            f"{cfg_path} is corrupt or missing required keys "
            f"({', '.join(required)}): {e}") from e
    return sc


def _restore_state(path: str, template, state_cls, fields: Sequence[str]):
    """Orbax-restore onto ``template`` and coerce dict/obj results back to
    the family's TrainState dataclass."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=jax.device_get(template))
    if isinstance(restored, state_cls):
        return restored
    return state_cls(**{
        f: getattr(restored, f) if hasattr(restored, f) else restored[f]
        for f in fields
    })


# --------------------------------------------------------- style family

def load_style_filter(ckpt_dir: str):
    """Rebuild the style_transfer Filter from a train checkpoint directory
    (the single loader behind ``serve --style-checkpoint`` and the tests)."""
    final = resolve_checkpoint_dir(ckpt_dir, "style", "train")
    sc = _read_sidecar(ckpt_dir, ("base_channels", "n_residual"))

    from dvf_tpu.ops import get_filter

    return get_filter(
        "style_transfer",
        params=load_params(final),
        base_channels=sc["base_channels"],
        n_residual=sc["n_residual"],
    )


def restore_checkpoint(
    path: str,
    template: TrainState,
    mesh=None,
    config: Optional[StyleTrainConfig] = None,
) -> TrainState:
    """Load a style TrainState from ``path``.

    ``template`` (e.g. a fresh ``init_train_state``) supplies the pytree
    structure. With ``mesh`` + ``config`` the restored state is placed
    straight onto the mesh per ``state_pspecs`` (resume-on-slice).
    """
    state = _restore_state(
        path, template, TrainState,
        ("params", "opt_state", "vgg_params", "style_grams", "step"),
    )
    if mesh is not None:
        state = shard_train_state(state, mesh, config or StyleTrainConfig())
    return state


# ----------------------------------------------------- SR (ESPCN) family

def load_sr_filter(ckpt_dir: str):
    """Rebuild the super_resolution Filter from a train-sr checkpoint dir
    (behind ``serve --sr-checkpoint``)."""
    final = resolve_checkpoint_dir(ckpt_dir, "sr", "train-sr")
    sc = _read_sidecar(ckpt_dir, ("scale",))

    from dvf_tpu.ops import get_filter

    return get_filter("super_resolution", params=load_params(final), scale=sc["scale"])


def restore_sr_checkpoint(path: str, template, mesh=None, config=None):
    """SR counterpart of :func:`restore_checkpoint` (template = a fresh
    ``train.sr.init_train_state``)."""
    from dvf_tpu.train.sr import SrTrainConfig, SrTrainState
    from dvf_tpu.train.sr import shard_train_state as shard_sr

    state = _restore_state(path, template, SrTrainState,
                           ("params", "opt_state", "step"))
    if mesh is not None:
        state = shard_sr(state, mesh, config or SrTrainConfig())
    return state


class AsyncSaver:
    """Non-blocking checkpoint writes for training loops (TPU-idiomatic:
    the device keeps stepping while orbax serializes to disk in the
    background).

    One in-flight save at a time: ``save()`` first waits for the previous
    write (usually already finished — checkpoint cadence >> write time),
    snapshots the state to host, and returns as soon as the async write
    is dispatched. ``close()`` drains the last write; without it a
    killed-right-after-save run could leave a torn final checkpoint (the
    step_* cadence means at most one checkpoint interval is lost either
    way — same at-most-once gap as the reference's dropped frames).
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str, state) -> str:
        path = os.path.abspath(path)
        self._ckptr.wait_until_finished()
        self._ckptr.save(path, jax.device_get(state), force=True)
        return path

    def close(self) -> None:
        self._ckptr.wait_until_finished()
        self._ckptr.close()
