"""Checkpoint / resume for style-transfer training (orbax-backed).

The reference has nothing persistent (SURVEY.md §5.4 — its pipeline is
stateless per frame); the framework's training loop does: net params, adam
moments, frozen VGG weights, target Grams, step counter. Orbax writes the
whole TrainState pytree; restore takes the abstract template (from
``init_train_state``) so dtypes/shapes — and on restore-onto-a-mesh, the
shardings — come back exactly.

Checkpoints are standard orbax directories: resumable across processes and
readable by any orbax tool.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from dvf_tpu.train.style import StyleTrainConfig, TrainState, shard_train_state


def save_checkpoint(path: str, state: TrainState) -> str:
    """Write ``state`` to ``path`` (an empty/new directory). Blocking."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-writing "final" (or a colliding step dir) on a
        # resumed run must overwrite, not crash the end of training.
        ckptr.save(path, jax.device_get(state), force=True)
    return path


def restore_checkpoint(
    path: str,
    template: TrainState,
    mesh=None,
    config: Optional[StyleTrainConfig] = None,
) -> TrainState:
    """Load a TrainState from ``path``.

    ``template`` (e.g. a fresh ``init_train_state``) supplies the pytree
    structure. With ``mesh`` + ``config`` the restored state is placed
    straight onto the mesh per ``state_pspecs`` (resume-on-slice).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=jax.device_get(template))
    state = TrainState(**{
        f: getattr(restored, f) if hasattr(restored, f) else restored[f]
        for f in ("params", "opt_state", "vgg_params", "style_grams", "step")
    }) if not isinstance(restored, TrainState) else restored
    if mesh is not None:
        state = shard_train_state(state, mesh, config or StyleTrainConfig())
    return state
