"""Perceptual-loss training for the style-transfer net, sharded over a mesh.

Loss = content (VGG feature MSE vs the input) + style (Gram-matrix MSE vs a
fixed style image's Grams) + total-variation smoothness — the Johnson et al.
recipe, computed entirely on device.

Sharding design — **explicit SPMD**, not GSPMD-auto: the whole train step
is one all-manual ``jax.shard_map`` over the mesh (see make_train_step for
the full rationale, including the XLA bugs that rule out the auto path on
this toolchain):
- batch: dim 0 sharded over 'data' AND 'space' folded together
  (``train_batch_sharding``) — both axes act as data parallelism here;
- net/VGG params + adam moments: Megatron column/row tensor-parallel specs
  over 'model' (``state_pspecs``), with explicit psum/all_gather
  collectives inside the forward (models.*.tp_inner_*);
- gradients: explicit ``lax.pmean`` over ('data', 'space').

The shard_map is jitted with donated state — zero steady-state allocation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dvf_tpu.utils.compat import shard_map

from dvf_tpu.models.layers import gram_matrix
from dvf_tpu.models.style_transfer import (
    StyleNetConfig,
    apply_style_net,
    init_style_net,
    param_pspecs,
    tp_inner_apply,
)
from dvf_tpu.models.vgg import (
    VGGConfig,
    init_vgg,
    tp_inner_features,
    vgg_features,
    vgg_param_pspecs,
)


@dataclasses.dataclass(frozen=True)
class StyleTrainConfig:
    net: StyleNetConfig = StyleNetConfig()
    vgg: VGGConfig = VGGConfig()
    content_weight: float = 1.0
    style_weight: float = 10.0
    tv_weight: float = 1e-4
    learning_rate: float = 1e-3


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    vgg_params: Any          # frozen perceptual encoder
    style_grams: List[jnp.ndarray]   # target Grams, one per VGG block
    step: jnp.ndarray


def _tv_loss(img: jnp.ndarray) -> jnp.ndarray:
    dh = img[:, 1:, :, :] - img[:, :-1, :, :]
    dw = img[:, :, 1:, :] - img[:, :, :-1, :]
    return jnp.mean(dh.astype(jnp.float32) ** 2) + jnp.mean(dw.astype(jnp.float32) ** 2)


def style_loss_fn(
    params: Any,
    batch: jnp.ndarray,
    vgg_params: Any,
    style_grams: List[jnp.ndarray],
    config: StyleTrainConfig,
    apply_fn=None,
    features_fn=None,
) -> Tuple[jnp.ndarray, dict]:
    """``apply_fn``/``features_fn`` default to the single-shard model fns;
    make_train_step passes the per-shard TP versions (tp_inner_apply /
    tp_inner_features) since it calls this inside an all-manual shard_map."""
    apply_fn = apply_fn or (lambda p, b: apply_style_net(p, b, config.net))
    features_fn = features_fn or (lambda p, b: vgg_features(p, b, config.vgg))
    out = apply_fn(params, batch)
    out_feats = features_fn(vgg_params, out)
    content_feats = features_fn(vgg_params, batch)
    content = sum(
        jnp.mean((a.astype(jnp.float32) - b.astype(jnp.float32)) ** 2)
        for a, b in zip(out_feats, content_feats)
    ) / len(out_feats)
    # Per-layer RELATIVE Gram error: raw Gram MSE scales with 1/(H·W·C)²
    # and sits orders of magnitude below the content term (measured ~1e-6
    # vs ~1e-2 at 64², which made the style term invisible at any sane
    # weight and trained nets that just desaturated). Dividing by the
    # target Gram's energy makes every layer O(1) and resolution-free.
    style = sum(
        jnp.mean((gram_matrix(f) - g[None]) ** 2)
        / (jnp.mean(g.astype(jnp.float32) ** 2) + 1e-12)
        for f, g in zip(out_feats, style_grams)
    ) / len(out_feats)
    tv = _tv_loss(out)
    loss = (
        config.content_weight * content
        + config.style_weight * style
        + config.tv_weight * tv
    )
    return loss, {"loss": loss, "content": content, "style": style, "tv": tv}


def make_optimizer(config: StyleTrainConfig) -> optax.GradientTransformation:
    return optax.adam(config.learning_rate)


def init_train_state(
    rng: jax.Array,
    style_image: jnp.ndarray,
    config: StyleTrainConfig = StyleTrainConfig(),
) -> TrainState:
    """Build params + opt state + precomputed style-target Grams.

    ``style_image``: (1, H, W, 3) float in [0, 1].
    """
    net_key, vgg_key = jax.random.split(rng)
    params = init_style_net(net_key, config.net)
    vgg_params = init_vgg(vgg_key, config.vgg)
    opt_state = make_optimizer(config).init(params)
    grams = [gram_matrix(f)[0] for f in vgg_features(vgg_params, style_image, config.vgg)]
    return TrainState(
        params=params,
        opt_state=opt_state,
        vgg_params=vgg_params,
        style_grams=grams,
        step=jnp.zeros((), jnp.int32),
    )


def state_pspecs(state: TrainState, config: StyleTrainConfig) -> TrainState:
    """PartitionSpec tree mirroring a TrainState (TP over 'model').

    Optimizer moments (adam mu/nu) mirror the param layout leaf-for-leaf:
    each opt-state leaf whose dict path resolves inside the param spec tree
    inherits that spec; scalars (step counts) replicate.
    """
    p_specs = param_pspecs(config.net)
    v_specs = vgg_param_pspecs(config.vgg)

    def opt_spec(path, _leaf):
        keys = [k.key for k in path if isinstance(k, jax.tree_util.DictKey)]
        node: Any = p_specs
        for k in keys:
            if not isinstance(node, dict) or k not in node:
                return P()
            node = node[k]
        return node if isinstance(node, P) else P()

    opt_specs = jax.tree_util.tree_map_with_path(opt_spec, state.opt_state)
    return TrainState(
        params=p_specs,
        opt_state=opt_specs,
        vgg_params=v_specs,
        style_grams=[P() for _ in state.style_grams],
        step=P(),
    )


def shard_train_state(state: TrainState, mesh: Mesh, config: StyleTrainConfig) -> TrainState:
    """Place a host TrainState onto the mesh per the TP layout."""
    specs = state_pspecs(state, config)

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return TrainState(
        params=jax.tree.map(put, state.params, specs.params),
        opt_state=jax.tree.map(put, state.opt_state, specs.opt_state),
        vgg_params=jax.tree.map(put, state.vgg_params, specs.vgg_params),
        style_grams=[put(g, s) for g, s in zip(state.style_grams, specs.style_grams)],
        step=put(state.step, specs.step),
    )


def train_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical batch sharding for training: DP over data×space combined
    (see the batch-layout note in make_train_step)."""
    return NamedSharding(mesh, P(("data", "space")))


def make_train_step(
    mesh: Mesh,
    config: StyleTrainConfig = StyleTrainConfig(),
    state_template: TrainState = None,
    donate: bool = True,
) -> Callable[[TrainState, jnp.ndarray], Tuple[TrainState, dict]]:
    """Build the jitted, mesh-sharded train step.

    The whole step is ONE all-manual ``shard_map`` over the mesh — the
    explicit-SPMD formulation (scaling-book style): every device runs the
    per-shard program below and all cross-device movement is an explicit
    named-axis collective:

    - dp (``data`` and ``space``, folded together on the batch dim):
      per-shard grads from the local micro-batch, then ``pmean`` over both
      axes. Spatially partitioning the conv net's H axis is deliberately
      NOT done here — GSPMD's spatial conv partitioner miscompiles when
      combined with TP on this toolchain (wrong halo values; and
      differentiating a mixed manual/auto shard_map crashes the XLA SPMD
      pass with "Invalid binary instruction opcode copy"). True spatial
      parallelism with hand-written halo exchange lives in the stencil
      filter path (dvf_tpu.parallel.halo).
    - tp (``model``): Megatron column/row convs with explicit ``psum``
      inside the forward (models.style_transfer.tp_inner_apply /
      models.vgg.tp_inner_features); grads of the psum are handled by AD.
    - adam runs per-shard on locally-owned param slices; (data, space)
      replicas compute identical updates deterministically.

    ``state_template`` provides the opt-state tree structure for the spec
    derivation (any TrainState from init_train_state).

    The returned fn maps ``(state, batch) -> (state, metrics)`` with batch
    sharded per :func:`train_batch_sharding` and state per ``state_pspecs``.
    """
    optimizer = make_optimizer(config)
    apply_fn = tp_inner_apply(config.net)
    features_fn = tp_inner_features(config.vgg)
    if state_template is None:
        raise ValueError("make_train_step needs a state_template TrainState")
    specs = state_pspecs(state_template, config)
    dp_axes = ("data", "space")

    def local_step(state: TrainState, batch: jnp.ndarray):
        grads, metrics = jax.grad(style_loss_fn, has_aux=True)(
            state.params, batch, state.vgg_params, state.style_grams, config,
            apply_fn, features_fn,
        )
        grads = lax.pmean(grads, dp_axes)
        metrics = lax.pmean(metrics, dp_axes)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            vgg_params=state.vgg_params,
            style_grams=state.style_grams,
            step=state.step + 1,
        )
        return new_state, metrics

    batch_spec = P(dp_axes)
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
