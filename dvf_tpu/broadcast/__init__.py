"""Broadcast plane: one stream in, tens of thousands of watchers out.

The serving tier below this package delivers every processed frame to
exactly ONE session — delivery cost scales 1:1 with viewers × codec
work, the reference's strictly-1:1 capture→display shape
(webcam_app.py). This package is the subscription layer ABOVE that
per-session delivery (ROADMAP item 2):

- a published session's output becomes a named **channel**;
- subscribers attach to a channel at a **tier** = (geometry, quality,
  wire) — each tier owns ONE closed-loop encoder (per-tier
  ``DeltaCodec`` state at the PR 7 seam), so encode cost is per-tier,
  never per-viewer (the encode-once invariant, pinned by counter
  asserts in tier-1);
- frames fan out through per-subscriber drop-oldest queues: a slow or
  dead subscriber is evicted from its OWN queue and can never stall
  the tier, the publisher, or the serving hot path;
- a **relay** node subscribes upstream and re-fans tiers to its own
  subscriber set without running any filter compute — fan-out capacity
  scales independently of device capacity, and the PR 14 audit
  envelope (stamped once, at the tier encoder) survives the relay hop
  verbatim to the final subscriber.
"""

from dvf_tpu.broadcast.abr import BroadcastAbrConfig, SubscriberAbr
from dvf_tpu.broadcast.channel import (
    BroadcastDelivery,
    Channel,
    Subscription,
    Tier,
    TierLane,
)
from dvf_tpu.broadcast.plane import BroadcastPlane, live_broadcast_sockets
from dvf_tpu.broadcast.relay import RelayNode, live_relay_nodes

__all__ = [
    "BroadcastAbrConfig",
    "BroadcastDelivery",
    "BroadcastPlane",
    "Channel",
    "RelayNode",
    "SubscriberAbr",
    "Subscription",
    "Tier",
    "TierLane",
    "live_broadcast_sockets",
    "live_relay_nodes",
]
