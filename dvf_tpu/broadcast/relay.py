"""Relay nodes: egress replicas that fan out without filter compute.

A relay subscribes UPSTREAM (to a channel on another plane — the
device-owning serving box) and re-fans what it receives to its OWN
subscriber set. Two paths per relay:

- **Forward (same tier)** — the relay-only hot path: the upstream
  payload ``bytes`` are distributed verbatim to this relay's
  subscribers. No decode, no re-encode, ``encodes_total`` stays 0 —
  and the PR 14 audit envelope (stamped once, at the upstream tier
  encoder) survives the hop untouched, so the FINAL subscriber's
  verify still proves end-to-end integrity across the relay. A
  ``chaos`` plan arms the ``corrupt_wire`` bit-flip ON the hop
  (after upstream stamping, before fan-out) — the injected corruption
  the downstream envelope check must catch.
- **Derived tiers** (optional) — the relay decodes the source tier
  once and feeds ordinary :class:`~dvf_tpu.broadcast.channel.TierLane`
  encoders, so a relay can also serve cheaper renditions without
  touching the upstream box (encode cost lands on the relay, still
  once per tier).

A watcher's latency through a relay still decomposes additively: the
relay appends a ``relay`` lineage mark to every forwarded delivery
(when the upstream plane armed lineage), so
``FrameLineage.components_ms()`` splits encode / fanout / relay /
deliver and sums to the end-to-end total (the PR 11 invariant).

Relays register in a module-level registry (``live_relay_nodes``) the
conftest session-end guard sweeps — a relay outliving its plane is a
leaked pump thread plus a pinned upstream subscription.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence

from dvf_tpu.broadcast.channel import (
    BroadcastDelivery,
    Subscription,
    Tier,
    TierLane,
)
from dvf_tpu.obs.audit import is_stamped, verify_wire
from dvf_tpu.transport.codec import make_wire_codec

_LIVE_RELAYS: "weakref.WeakSet" = weakref.WeakSet()


def live_relay_nodes() -> list:
    """Relay nodes whose pump thread is still alive (conftest guard)."""
    return [r for r in _LIVE_RELAYS if r.alive()]


class _ForwardLane:
    """The relay-only lane: per-subscriber queues, zero codec state.
    Single-writer (the relay pump thread), same locking discipline as
    :class:`TierLane` but with nothing to encode."""

    def __init__(self, tier: Tier, sub_queue: int, evict_after: int):
        self.tier = tier
        self.sub_queue = sub_queue
        self.evict_after = max(1, evict_after)
        self.forwarded_total = 0
        self._subs: Dict[str, Subscription] = {}
        self._lock = threading.Lock()
        self._gone_subs = 0
        self._gone_delivered = 0
        self._gone_dropped = 0
        self._evictions = 0

    def subscribe(self, sub: Subscription) -> None:
        # Forwarded payloads are whatever the upstream lane emitted —
        # including delta frames this joiner cannot composite without a
        # keyframe. The relay cannot force one (it owns no encoder);
        # joiners wait unsynced for the upstream cadence keyframe, the
        # same bounded staleness as a suppressed re-key upstream.
        sub.tier = self.tier
        sub.synced = self.tier.wire != "delta"
        with self._lock:
            self._subs[sub.id] = sub

    def unsubscribe(self, sub_id: str, evicted: bool = False):
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            if sub is None:
                return None
            self._gone_subs += 1
            self._gone_delivered += sub.delivered
            self._gone_dropped += sub.queue.dropped
            if evicted:
                self._evictions += 1
                sub.evicted = True
        return sub

    def forward(self, d: BroadcastDelivery) -> None:
        with self._lock:
            subs = list(self._subs.values())
        evict = None
        for sub in subs:
            streak = sub.offer(d)
            self.forwarded_total += 1
            if streak >= self.evict_after:
                if evict is None:
                    evict = []
                evict.append(sub.id)
        if evict:
            for sid in evict:
                self.unsubscribe(sid, evicted=True)

    def stats(self) -> dict:
        with self._lock:
            subs = {s.id: s.stats() for s in self._subs.values()}
            live_delivered = sum(s.delivered for s in self._subs.values())
            live_dropped = sum(s.queue.dropped for s in self._subs.values())
            gone = (self._gone_subs, self._gone_delivered,
                    self._gone_dropped, self._evictions)
        return {
            "tier": self.tier.label(),
            "subscribers": subs,
            "subscriber_count": len(subs),
            "forwarded_total": self.forwarded_total,
            "encodes_total": 0,  # the relay-only claim, as a datum
            "delivered_total": gone[1] + live_delivered,
            "dropped_total": gone[2] + live_dropped,
            "churned_subscribers_total": gone[0],
            "evicted_subscribers_total": gone[3],
        }

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
        for sid in subs:
            self.unsubscribe(sid)


class RelayNode:
    """One egress replica: upstream subscription in, tiers out."""

    def __init__(self, relay_id: str, upstream, channel: str,
                 source_tier: Tier, tiers: Sequence[Tier] = (),
                 sub_queue: int = 8, evict_after: int = 32,
                 upstream_queue: int = 32, chaos: Any = None,
                 codec_threads: int = 2, keyframe_interval: int = 16,
                 delta_tile: int = 32):
        self.id = relay_id
        self.channel = channel
        self.source_tier = source_tier
        self.chaos = chaos
        self.relayed_total = 0        # upstream deliveries pumped
        self.corrupted_on_hop = 0     # chaos flips actually applied
        self._upstream_sub = upstream.subscribe(
            channel, tier=source_tier, queue_size=upstream_queue,
            sub_id=f"relay-{relay_id}")
        self.forward_lane = _ForwardLane(source_tier, sub_queue, evict_after)
        self._derived: Dict[Tier, TierLane] = {}
        self._decoder = None
        for t in tiers:
            if t != source_tier:
                self._derived[t] = TierLane(
                    t, f"{channel}~{relay_id}", sub_queue=sub_queue,
                    evict_after=evict_after, codec_threads=codec_threads,
                    keyframe_interval=keyframe_interval,
                    delta_tile=delta_tile)
        if self._derived:
            st = source_tier
            if st.wire == "raw":
                # A raw payload carries no geometry; the relay would be
                # guessing shapes. Derive from a self-describing wire.
                raise ValueError(
                    "derived relay tiers need a jpeg/delta source tier "
                    "(raw payloads are shapeless on the wire)")
            kw = ({"tile": delta_tile, "keyframe_interval": keyframe_interval,
                   "on_gap": "composite"} if st.wire == "delta" else {})
            self._decoder = make_wire_codec(
                st.wire, quality=st.quality, threads=codec_threads, **kw)
        self._sub_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pump = threading.Thread(
            target=self._pump_loop, name=f"dvf-bcast-relay-{relay_id}",
            daemon=True)
        self._pump.start()
        _LIVE_RELAYS.add(self)

    def alive(self) -> bool:
        return self._pump.is_alive()

    # -- subscriber side -------------------------------------------------

    def subscribe(self, tier: Optional[Tier] = None,
                  queue_size: Optional[int] = None) -> Subscription:
        tier = tier or self.source_tier
        with self._lock:
            sub_id = f"{self.id}-sub-{self._sub_seq}"
            self._sub_seq += 1
        sub = Subscription(sub_id, self.channel, tier,
                           queue_size=queue_size or self.forward_lane.sub_queue)
        if tier == self.source_tier:
            self.forward_lane.subscribe(sub)
        else:
            lane = self._derived.get(tier)
            if lane is None:
                raise ValueError(
                    f"relay {self.id} does not serve tier {tier.label()} "
                    f"(source {self.source_tier.label()}, derived "
                    f"{[t.label() for t in self._derived]})")
            lane.subscribe(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        if sub.tier == self.source_tier:
            self.forward_lane.unsubscribe(sub.id)
        else:
            lane = self._derived.get(sub.tier)
            if lane is not None:
                lane.unsubscribe(sub.id)

    # -- pump -------------------------------------------------------------

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            got = self._upstream_sub.poll(64)
            if not got:
                self._stop.wait(0.005)
                continue
            for d in got:
                self.relayed_total += 1
                payload = d.payload
                if self.chaos is not None:
                    flipped = self.chaos.flip_bit("corrupt_wire", payload)
                    if flipped is not payload:
                        self.corrupted_on_hop += 1
                    payload = flipped
                marks = None
                lin = d.lineage
                if lin is not None:
                    lin.mark("relay")
                    marks = list(lin.marks)
                self.forward_lane.forward(BroadcastDelivery(
                    d.seq, payload, d.capture_ts, d.keyframe, lin))
                if self._derived:
                    self._feed_derived(d, payload, marks)

    def _feed_derived(self, d: BroadcastDelivery, payload: bytes,
                      marks) -> None:
        """Decode the source payload once, feed every derived lane. A
        payload that fails envelope verification or decode is dropped
        here (the forward path already carried the corrupt bytes to
        ITS subscribers' verifiers — derived tiers must not re-encode
        garbage into fresh, validly-stamped frames)."""
        try:
            inner = payload
            if is_stamped(inner):
                inner = verify_wire(inner, hop=f"relay:{self.id}")
            frame = self._decoder.decode(inner)
        except Exception:  # noqa: BLE001 — corrupt hop payload: contained
            return
        for lane in self._derived.values():
            lane.offer(d.seq, frame, d.capture_ts, marks=marks)

    # -- observability / lifecycle ----------------------------------------

    def stats(self) -> dict:
        return {
            "channel": self.channel,
            "source_tier": self.source_tier.label(),
            "relayed_total": self.relayed_total,
            "corrupted_on_hop_total": self.corrupted_on_hop,
            "upstream_dropped_total": self._upstream_sub.queue.dropped,
            "forward": self.forward_lane.stats(),
            **({"tiers": {t.label(): lane.stats()
                          for t, lane in self._derived.items()}}
               if self._derived else {}),
        }

    def close(self, upstream=None, timeout: float = 5.0) -> None:
        self._stop.set()
        self._pump.join(timeout=timeout)
        if upstream is not None:
            upstream.unsubscribe(self._upstream_sub)
        self.forward_lane.close()
        for lane in self._derived.values():
            lane.close()
        if self._decoder is not None and hasattr(self._decoder, "close"):
            self._decoder.close()
        _LIVE_RELAYS.discard(self)
