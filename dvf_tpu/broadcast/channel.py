"""Channels, tiers, and the encode-once fan-out lanes.

Dataflow (one channel):

  publisher session → tap (one frame copy) → ingest queue (drop-oldest)
    → fan-out worker thread:  for each TIER LANE:
        downscale (tier geometry) → encode ONCE (the lane's closed-loop
        codec) → audit-stamp ONCE → put into EVERY subscriber's own
        drop-oldest queue (a bytes reference — no per-viewer copy)

The invariants this module owns:

- **Encode-once**: a lane's codec runs exactly once per offered frame
  regardless of subscriber count (``TierLane.encodes_total`` is the
  counter the tier-1 assert pins). Fan-out is reference distribution of
  immutable ``bytes`` — per-viewer cost is one queue append.
- **Isolation**: every subscriber owns a bounded drop-oldest queue. A
  slow consumer drops ITS OWN frames; one that stops draining entirely
  is evicted from the lane after ``evict_after`` consecutive displaced
  puts. Neither ever blocks the lane, the channel worker, the
  publisher, or any other subscriber — the other subscribers' payload
  sequences are bit-identical to a run where the slow peer never
  existed (pinned in tier-1).
- **Rate-limited re-key, per TIER**: a late joiner on a delta tier
  needs a keyframe to sync. The request goes through the lane's forced-
  keyframe limiter — the ring transport's eviction re-key discipline
  (transport.ring_queue): the first request re-keys immediately, then
  at most one forced keyframe per ``keyframe_interval // 2`` encodes.
  A 1k-subscriber join burst costs ONE keyframe per tier, not a
  keyframe storm (joiners wait in ``synced=False`` until it lands —
  delta frames before their first keyframe are skipped, not queued).
- **Closed-loop determinism**: the lane encodes every frame the worker
  hands it, in channel-sequence order, so a delta lane's payload stream
  is exactly what an identically-configured ``DeltaCodec`` produces
  over the publisher's own delivered frames — the byte-identical
  subscriber-vs-publisher property tier-1 pins.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from dvf_tpu.obs.lineage import FrameLineage
from dvf_tpu.sched.queues import DropOldestQueue
from dvf_tpu.transport.codec import WIRE_MODES, make_wire_codec


@dataclasses.dataclass(frozen=True)
class Tier:
    """One broadcast rendition: (geometry, quality, wire).

    ``geometry`` is the delivered (h, w) — ``None`` means the
    publisher's native geometry (no resample). ``quality`` feeds the
    tier codec (ignored by the raw wire). ``wire`` is the payload
    format every subscriber on this tier receives
    (:data:`~dvf_tpu.transport.codec.WIRE_MODES`).
    """

    geometry: Optional[Tuple[int, int]] = None
    quality: int = 90
    wire: str = "jpeg"

    def __post_init__(self):
        if self.wire not in WIRE_MODES:
            raise ValueError(
                f"tier wire must be one of {WIRE_MODES}, got {self.wire!r}")
        if self.geometry is not None:
            h, w = self.geometry
            if h <= 0 or w <= 0:
                raise ValueError(f"bad tier geometry {self.geometry}")
            object.__setattr__(self, "geometry", (int(h), int(w)))
        if not (1 <= int(self.quality) <= 100):
            raise ValueError(f"tier quality must be 1..100, "
                             f"got {self.quality!r}")

    def label(self) -> str:
        g = ("native" if self.geometry is None
             else f"{self.geometry[1]}x{self.geometry[0]}")
        return f"{g}/q{self.quality}/{self.wire}"

    @classmethod
    def parse(cls, spec: str) -> "Tier":
        """``"native/q90/jpeg"`` / ``"640x360/q60/delta"`` (WxH, the
        display convention) → Tier. Parts after the geometry may appear
        in any order; missing parts take the defaults."""
        geometry = None
        quality, wire = 90, "jpeg"
        for part in spec.strip().split("/"):
            part = part.strip()
            if not part or part == "native":
                continue
            if part.startswith("q") and part[1:].isdigit():
                quality = int(part[1:])
            elif part in WIRE_MODES:
                wire = part
            elif "x" in part:
                w_s, _, h_s = part.partition("x")
                geometry = (int(h_s), int(w_s))
            else:
                raise ValueError(f"unparseable tier component {part!r} "
                                 f"in {spec!r}")
        return cls(geometry=geometry, quality=quality, wire=wire)

    def cost_key(self) -> Tuple[float, int]:
        """Ladder ordering key: bigger = more expensive rendition.
        Native geometry sorts above every fixed geometry."""
        area = (float("inf") if self.geometry is None
                else float(self.geometry[0] * self.geometry[1]))
        return (area, int(self.quality))


def downscale(frame: np.ndarray, geometry: Tuple[int, int]) -> np.ndarray:
    """Deterministic nearest-neighbor resample to ``(h, w)`` — pure
    index arithmetic, no interpolation state, so the same frame always
    produces the same bytes (the closed-loop tier codec depends on
    that). Upscaling works too (repeated rows), though tiers normally
    go down the ladder."""
    h, w = geometry
    if frame.shape[:2] == (h, w):
        return frame
    ridx = (np.arange(h) * frame.shape[0]) // h
    cidx = (np.arange(w) * frame.shape[1]) // w
    return np.ascontiguousarray(frame[ridx][:, cidx])


class BroadcastDelivery(NamedTuple):
    """One payload popped from a subscription queue."""

    seq: int             # channel-wide frame sequence number
    payload: bytes       # tier wire bytes (audit-stamped when armed)
    capture_ts: float    # publisher delivery timestamp
    keyframe: bool       # self-contained payload (always True off-delta)
    lineage: Any = None  # FrameLineage when the plane armed lineage


class Subscription:
    """One watcher's attachment to a tier lane.

    The queue is the ONLY coupling to the lane: ``poll`` may be called
    from any client thread; the lane's fan-out worker only ever does a
    non-blocking put. ``tier`` mutates when ABR moves the subscription
    between lanes (the handle stays valid across moves).
    """

    def __init__(self, sub_id: str, channel: str, tier: Tier,
                 queue_size: int = 8, abr: Optional[Any] = None):
        self.id = sub_id
        self.channel = channel
        self.tier = tier
        self.queue = DropOldestQueue(maxsize=queue_size)
        self.abr = abr                 # SubscriberAbr when ABR is armed
        self.synced = tier.wire != "delta"  # delta joiners wait for a key
        self.offered = 0               # frames the lane showed this sub
        self.enqueued = 0              # frames that entered the queue
        self.skipped_unsynced = 0      # delta frames before the first key
        self.delivered = 0             # frames the client actually popped
        self.tier_shifts = 0           # ABR moves (both directions)
        self.evicted = False
        self._consecutive_drops = 0    # displaced puts since last poll
        self._lock = threading.Lock()

    # -- lane side (fan-out worker thread) ------------------------------

    def offer(self, d: BroadcastDelivery) -> int:
        """Non-blocking enqueue; returns the consecutive-drop streak
        (0 when the put displaced nothing)."""
        with self._lock:
            self.offered += 1
            if not self.synced:
                if not d.keyframe:
                    self.skipped_unsynced += 1
                    return 0
                self.synced = True
            evicted = self.queue.put(d)
            if evicted is not None:
                self._consecutive_drops += 1
            self.enqueued += 1
            return self._consecutive_drops

    # -- client side ----------------------------------------------------

    def poll(self, max_n: int = 64) -> List[BroadcastDelivery]:
        got = self.queue.pop_up_to(max_n)
        if got:
            now = time.time()
            with self._lock:
                self.delivered += len(got)
                self._consecutive_drops = 0
            for d in got:
                if d.lineage is not None:
                    d.lineage.mark("deliver", now)
        return got

    def stats(self) -> dict:
        with self._lock:
            return {
                "tier": self.tier.label(),
                "offered": self.offered,
                "enqueued": self.enqueued,
                "delivered": self.delivered,
                "dropped": self.queue.dropped,
                "skipped_unsynced": self.skipped_unsynced,
                "queue_depth": len(self.queue),
                "tier_shifts": self.tier_shifts,
                "synced": self.synced,
                "evicted": self.evicted,
            }


class TierLane:
    """One tier's encoder + subscriber set inside a channel.

    Single-writer: ``offer`` runs only on the owning channel's fan-out
    worker thread (or a relay's pump thread), so the codec needs no
    lock. ``subscribe``/``unsubscribe``/``request_keyframe`` may come
    from any thread and only touch lock-guarded subscriber/limiter
    state.
    """

    def __init__(self, tier: Tier, channel: str,
                 keyframe_interval: int = 16, delta_tile: int = 32,
                 codec_threads: int = 2, sub_queue: int = 8,
                 evict_after: int = 32, audit: Any = None,
                 lineage: bool = False):
        self.tier = tier
        self.channel = channel
        self.keyframe_interval = keyframe_interval
        self.delta_tile = delta_tile
        self.codec_threads = codec_threads
        self.sub_queue = sub_queue
        self.evict_after = max(1, evict_after)
        self.audit = audit             # obs.audit.WireAudit or None
        self.lineage = lineage
        self.codec = None              # built lazily at first offer (the
        #   raw wire and native geometry both need the frame shape)
        self.encodes_total = 0         # THE encode-once counter
        self.fanout_total = 0          # payload references distributed
        self.keyframe_requests = 0     # join/drop re-key asks (pre-limit)
        self.keyframes_forced = 0      # asks that got through the limiter
        self._subs: Dict[str, Subscription] = {}
        self._lock = threading.Lock()
        # The ring transport's eviction re-key discipline, scoped per
        # TIER: first request re-keys immediately; under a sustained
        # join/drop storm at most one forced key per interval/2 encodes.
        self._force_cooldown = max(4, keyframe_interval // 2)
        self._encodes_since_forced = self._force_cooldown
        self._rekey_pending = False
        # Lifetime floors: counters of subscribers that were evicted or
        # closed — the lane's totals stay monotone across churn (PR 8).
        self._gone_subs = 0
        self._gone_delivered = 0
        self._gone_dropped = 0
        self._evictions = 0

    # -- membership (any thread) ----------------------------------------

    def subscribe(self, sub: Subscription) -> None:
        sub.tier = self.tier
        sub.synced = self.tier.wire != "delta"
        with self._lock:
            self._subs[sub.id] = sub
        if self.tier.wire == "delta":
            self.request_keyframe()

    def unsubscribe(self, sub_id: str, evicted: bool = False) -> Optional[
            Subscription]:
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            if sub is None:
                return None
            self._gone_subs += 1
            self._gone_delivered += sub.delivered
            self._gone_dropped += sub.queue.dropped
            if evicted:
                self._evictions += 1
                sub.evicted = True
        return sub

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def request_keyframe(self) -> bool:
        """Ask the closed-loop codec for a keyframe, through the per-tier
        limiter. Returns True when the request will be honored (the next
        encode re-keys); False when the cooldown suppressed it (a recent
        keyframe — or one already pending — covers this joiner)."""
        with self._lock:
            self.keyframe_requests += 1
            if self.tier.wire != "delta":
                return True  # every payload is already self-contained
            if self._rekey_pending:
                return False
            if self._encodes_since_forced < self._force_cooldown:
                return False
            self._rekey_pending = True
            return True

    # -- fan-out (single worker thread) ---------------------------------

    def _build_codec(self, shape: Tuple[int, ...]):
        t = self.tier
        kw = {}
        if t.wire == "delta":
            kw = {"tile": self.delta_tile,
                  "keyframe_interval": self.keyframe_interval}
        self.codec = make_wire_codec(
            t.wire, quality=t.quality, threads=self.codec_threads,
            raw_shape=shape, **kw)

    def offer(self, seq: int, frame: np.ndarray, ts: float,
              marks: Optional[list] = None) -> bytes:
        """Encode ``frame`` once and distribute the payload to every
        subscriber's queue; returns the wire payload (relays feed their
        forward path from it). ``marks`` is the upstream lineage trail
        (e.g. a relay hop) to prepend when lineage is armed."""
        t = self.tier
        if t.geometry is not None:
            frame = downscale(frame, t.geometry)
        if self.codec is None:
            self._build_codec(frame.shape)
        with self._lock:
            rekey = self._rekey_pending
            self._rekey_pending = False
        if rekey:
            self.codec.force_keyframe()
            self.keyframes_forced += 1
            self._encodes_since_forced = 0
        if t.wire == "raw":
            payload, was_key = frame.tobytes(), True
        elif t.wire == "delta":
            k0 = self.codec.keyframes
            payload = self.codec.encode(frame)
            was_key = self.codec.keyframes > k0
        else:
            payload, was_key = self.codec.encode(frame), True
        self.encodes_total += 1
        self._encodes_since_forced += 1
        if self.audit is not None:
            payload = self.audit.stamp(payload)
        lin = None
        if self.lineage:
            lin = FrameLineage(f"{self.channel}@{t.label()}", seq, ts)
            if marks:
                lin.marks.extend(marks)
            lin.mark("encode")
        with self._lock:
            subs = list(self._subs.values())
        evict = None
        for sub in subs:
            slin = lin
            if lin is not None and len(subs) > 1:
                # Lineage objects are mutated at deliver: each sub needs
                # its own copy (cheap: a list of 2-3 tuples).
                slin = FrameLineage(lin.session_id, seq, ts)
                slin.marks = list(lin.marks)
            streak = sub.offer(BroadcastDelivery(
                seq, payload, ts, was_key, slin))
            self.fanout_total += 1
            if streak >= self.evict_after:
                if evict is None:
                    evict = []
                evict.append(sub.id)
        if lin is not None:
            lin.mark("fanout")
        if evict:
            for sid in evict:
                self.unsubscribe(sid, evicted=True)
        return payload

    # -- observability / lifecycle --------------------------------------

    def stats(self) -> dict:
        with self._lock:
            subs = {s.id: s.stats() for s in self._subs.values()}
            live_delivered = sum(s.delivered for s in self._subs.values())
            live_dropped = sum(s.queue.dropped for s in self._subs.values())
            gone = (self._gone_subs, self._gone_delivered,
                    self._gone_dropped, self._evictions)
        depth = sum(s["queue_depth"] for s in subs.values())
        return {
            "tier": self.tier.label(),
            "wire": self.tier.wire,
            "subscribers": subs,
            "subscriber_count": len(subs),
            "queue_depth": depth,
            "encodes_total": self.encodes_total,
            "fanout_frames_total": self.fanout_total,
            "delivered_total": gone[1] + live_delivered,
            "dropped_total": gone[2] + live_dropped,
            "churned_subscribers_total": gone[0],
            "evicted_subscribers_total": gone[3],
            "keyframe_requests_total": self.keyframe_requests,
            "keyframes_forced_total": self.keyframes_forced,
            **({"codec": self.codec.stats()}
               if self.codec is not None and hasattr(self.codec, "stats")
               else {}),
            **({"audit": self.audit.stats()}
               if self.audit is not None else {}),
        }

    def close(self) -> None:
        with self._lock:
            subs = list(self._subs)
        for sid in subs:
            self.unsubscribe(sid)
        if self.codec is not None and hasattr(self.codec, "close"):
            self.codec.close()


class Channel:
    """One published stream's fan-out hub: the ingest queue the
    publisher's tap feeds, the fan-out worker thread, and the tier
    lanes. Construction and teardown belong to the
    :class:`~dvf_tpu.broadcast.plane.BroadcastPlane`."""

    def __init__(self, name: str, publisher: str = "",
                 tiers: Sequence[Tier] = (), ingest_depth: int = 8,
                 keyframe_interval: int = 16, delta_tile: int = 32,
                 codec_threads: int = 2, sub_queue: int = 8,
                 evict_after: int = 32, audit_wire: bool = False,
                 chaos: Any = None, lineage: bool = False):
        self.name = name
        self.publisher = publisher
        self._lane_kw = dict(
            keyframe_interval=keyframe_interval, delta_tile=delta_tile,
            codec_threads=codec_threads, sub_queue=sub_queue,
            evict_after=evict_after, lineage=lineage)
        self.audit_wire = audit_wire
        self.chaos = chaos
        self.lineage = lineage
        self.sub_queue = sub_queue
        self._lanes: Dict[Tier, TierLane] = {}
        self._ingest = DropOldestQueue(maxsize=ingest_depth)
        self._lock = threading.Lock()
        self._seq = 0
        self._sub_seq = 0
        self.offered_total = 0
        self.fanned_out_total = 0
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._worker = threading.Thread(
            target=self._fanout_loop, name=f"dvf-bcast-{name}", daemon=True)
        self._worker.start()
        for t in tiers:
            self.add_tier(t)

    # -- tiers ----------------------------------------------------------

    def _make_audit(self, tier: Tier):
        if not self.audit_wire:
            return None
        from dvf_tpu.obs.audit import WireAudit

        return WireAudit(f"broadcast:{self.name}/{tier.label()}",
                         chaos=self.chaos)

    def add_tier(self, tier: Tier) -> TierLane:
        with self._lock:
            lane = self._lanes.get(tier)
            if lane is None:
                lane = TierLane(tier, self.name, audit=self._make_audit(tier),
                                **self._lane_kw)
                self._lanes[tier] = lane
            return lane

    def ladder(self) -> List[Tier]:
        """Registered tiers, most expensive first — the ABR ladder
        (downshift moves toward the end)."""
        with self._lock:
            return sorted(self._lanes, key=Tier.cost_key, reverse=True)

    # -- publish side ----------------------------------------------------

    def offer(self, index: int, frame: np.ndarray, ts: float) -> None:
        """Publisher tap: ONE frame copy (the publisher's client may
        mutate the delivered array after poll), one bounded enqueue.
        Never blocks — under fan-out pressure the ingest queue drops its
        oldest, which every lane simply never sees (delta lanes are
        unaffected: their closed loop only advances on encoded frames)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        self.offered_total += 1
        self._ingest.put((seq, np.array(frame, copy=True), ts))
        self._idle.clear()

    # -- subscribe side --------------------------------------------------

    def subscribe(self, tier: Optional[Tier] = None,
                  queue_size: Optional[int] = None,
                  abr: Optional[Any] = None,
                  sub_id: Optional[str] = None) -> Subscription:
        ladder = self.ladder()
        if tier is None:
            if not ladder:
                raise ValueError(f"channel {self.name!r} has no tiers")
            tier = ladder[-1] if abr is not None else ladder[0]
        lane = self.add_tier(tier)
        if sub_id is None:
            with self._lock:
                sub_id = f"{self.name}-sub-{self._sub_seq}"
                self._sub_seq += 1
        sub = Subscription(sub_id, self.name, tier,
                           queue_size=queue_size or self.sub_queue,
                           abr=abr)
        lane.subscribe(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            lane = self._lanes.get(sub.tier)
        if lane is not None:
            lane.unsubscribe(sub.id)

    def move_subscription(self, sub: Subscription, target: Tier) -> bool:
        """ABR actuator: detach from the current lane, join ``target``
        (late-join discipline: delta targets wait for a rate-limited
        keyframe). The handle's queue survives the move — frames already
        queued at the old tier drain normally."""
        with self._lock:
            src = self._lanes.get(sub.tier)
        if src is None or target == sub.tier:
            return False
        if src.unsubscribe(sub.id) is None:
            return False  # concurrently evicted
        lane = self.add_tier(target)
        sub.tier_shifts += 1
        lane.subscribe(sub)
        return True

    # -- fan-out worker ---------------------------------------------------

    def _fanout_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._ingest.get(timeout=0.05)
            except TimeoutError:
                self._idle.set()
                continue
            items = [item] + self._ingest.pop_up_to(len(self._ingest))
            with self._lock:
                lanes = list(self._lanes.values())
            for seq, frame, ts in items:
                for lane in lanes:
                    lane.offer(seq, frame, ts)
                self.fanned_out_total += 1
                self._abr_tick(lanes, seq)
            if len(self._ingest) == 0:
                self._idle.set()

    def _abr_tick(self, lanes: List[TierLane], seq: int) -> None:
        """Drive every ABR-armed subscriber's controller off its own
        queue counters (deterministic: sampled on channel sequence, no
        wall clock). Runs on the fan-out thread, so tier moves never
        race the lanes' single-writer contract."""
        moves = []
        for lane in lanes:
            with lane._lock:
                subs = [s for s in lane._subs.values() if s.abr is not None]
            for sub in subs:
                want = sub.abr.step(sub, seq)
                if want is not None:
                    moves.append((sub, want))
        if not moves:
            return
        ladder = self.ladder()
        for sub, direction in moves:
            try:
                i = ladder.index(sub.tier)
            except ValueError:
                continue
            j = i + 1 if direction == "down" else i - 1
            if 0 <= j < len(ladder):
                self.move_subscription(sub, ladder[j])

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every offered frame has been fanned out (tests and
        graceful teardown); True on quiescence within ``timeout``."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self._ingest) == 0 and self._idle.wait(0.02):
                return True
        return False

    # -- observability / lifecycle ----------------------------------------

    def stats(self) -> dict:
        with self._lock:
            lanes = dict(self._lanes)
        return {
            "publisher": self.publisher,
            "offered_total": self.offered_total,
            "fanned_out_total": self.fanned_out_total,
            "ingest_depth": len(self._ingest),
            "ingest_dropped_total": self._ingest.dropped,
            "tier_count": len(lanes),
            "tiers": {t.label(): lane.stats() for t, lane in lanes.items()},
        }

    def close(self, timeout: float = 5.0) -> None:
        self.flush(timeout=min(1.0, timeout))
        self._stop.set()
        self._worker.join(timeout=timeout)
        with self._lock:
            lanes = list(self._lanes.values())
            self._lanes.clear()
        for lane in lanes:
            lane.close()
