"""Broadcast ABR: per-subscriber tier ladder moves from egress pressure.

The serve control plane's quality discipline (control.controllers.
QualityController) applied to the broadcast ladder: deterministic
transducers — no wall clock, no randomness — that observe ONE
subscriber's own queue counters and emit at most one ladder step at a
time, with streak hysteresis and a dwell so a borderline watcher does
not flap between tiers. Pressure here is the subscriber's OWN
drop-oldest queue displacing frames (egress backpressure: the client
is not draining fast enough for the tier's payload rate) — never a
shared signal, so one slow watcher only ever moves itself.

Sampling is on channel frame sequence (every ``sample_every`` fanned
frames), which makes replay exact: the same delivery/drop pattern
always produces the same tier trajectory.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class BroadcastAbrConfig:
    sample_every: int = 8        # controller cadence, in fanned frames
    drop_frac_high: float = 0.25  # window drop fraction ≥ this = pressure
    down_after: int = 2          # pressured samples per downshift
    up_after: int = 6            # clean samples per upshift
    min_dwell: int = 4           # samples between opposite-direction moves

    def __post_init__(self):
        if self.sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        if not (0.0 < self.drop_frac_high <= 1.0):
            raise ValueError("drop_frac_high must be in (0, 1]")


class SubscriberAbr:
    """One subscriber's ladder controller (single-owner: stepped only by
    the channel's fan-out thread, so no locking)."""

    def __init__(self, config: Optional[BroadcastAbrConfig] = None):
        self.config = config or BroadcastAbrConfig()
        self.samples = 0
        self.downshifts = 0
        self.upshifts = 0
        self._pressure_streak = 0
        self._clean_streak = 0
        self._last_move_sample = None   # (sample index, direction)
        self._last_offered = 0
        self._last_dropped = 0
        self._next_seq = None

    def _dwell_ok(self, direction: str) -> bool:
        if self._last_move_sample is None:
            return True
        at, last_dir = self._last_move_sample
        if last_dir == direction:
            return True  # same direction: the streaks already gate
        return (self.samples - at) >= self.config.min_dwell

    def step(self, sub, seq: int) -> Optional[str]:
        """Observe ``sub``'s lifetime queue counters at channel frame
        ``seq``; returns ``"down"`` / ``"up"`` / None. The window is the
        counter delta since the previous sample."""
        cfg = self.config
        if self._next_seq is None:
            self._next_seq = seq + cfg.sample_every
            self._last_offered = sub.offered
            self._last_dropped = sub.queue.dropped
            return None
        if seq < self._next_seq:
            return None
        self._next_seq = seq + cfg.sample_every
        self.samples += 1
        offered = sub.offered
        dropped = sub.queue.dropped
        d_off = offered - self._last_offered
        d_drop = dropped - self._last_dropped
        self._last_offered = offered
        self._last_dropped = dropped
        pressured = d_off > 0 and (d_drop / d_off) >= cfg.drop_frac_high
        if pressured:
            self._pressure_streak += 1
            self._clean_streak = 0
        else:
            self._clean_streak += 1
            self._pressure_streak = 0
        if (pressured and self._pressure_streak >= cfg.down_after
                and self._dwell_ok("down")):
            self._pressure_streak = 0
            self.downshifts += 1
            self._last_move_sample = (self.samples, "down")
            return "down"
        if (not pressured and self._clean_streak >= cfg.up_after
                and self._dwell_ok("up")):
            self._clean_streak = 0
            self.upshifts += 1
            self._last_move_sample = (self.samples, "up")
            return "up"
        return None

    def stats(self) -> dict:
        return {
            "samples": self.samples,
            "downshifts": self.downshifts,
            "upshifts": self.upshifts,
        }
