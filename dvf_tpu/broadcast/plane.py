"""BroadcastPlane — the channel registry + the fan-out front doors.

One plane per serving frontend (serve or fleet): it owns every
published channel, the relay nodes spawned off this box, and the
optional ZMQ gate remote watchers attach through. Everything exports
through the PR 8 registry discipline:

- ``signals()`` — flat ``broadcast_*`` series with MONOTONE lifetime
  floors: a closed channel / evicted subscriber / retired relay folds
  its totals into ``_closed_totals`` first, so ``broadcast_*_total``
  never decreases across churn (the scrape-side rate() contract);
- ``stats()`` — the nested per-channel/tier/subscriber rows (dynamic
  keys, registered in ``obs.registry.DYNAMIC_KEY_PARENTS``).
"""

from __future__ import annotations

import json
import threading
import weakref
from typing import Any, Dict, Optional, Sequence, Union

from dvf_tpu.broadcast.abr import BroadcastAbrConfig, SubscriberAbr
from dvf_tpu.broadcast.channel import Channel, Subscription, Tier
from dvf_tpu.broadcast.relay import RelayNode
from dvf_tpu.resilience.continuity import ContinuityStats, LivenessMonitor

_LIVE_GATES: "weakref.WeakSet" = weakref.WeakSet()


def live_broadcast_sockets() -> list:
    """ZMQ gate endpoints still open (conftest session-end guard): a
    gate outliving its plane pins a bound socket + server thread."""
    return [g for g in _LIVE_GATES if not g.closed]


_FLOOR_KEYS = (
    "encodes", "fanout_frames", "delivered", "dropped", "ingest_dropped",
    "churned_subscribers", "evicted_subscribers", "keyframes_forced",
    "relayed", "relay_forwarded", "relay_corrupted_on_hop",
)


class BroadcastPlane:
    """Channel/relay registry for one serving frontend."""

    def __init__(self, audit_wire: bool = False, chaos: Any = None,
                 ingest_depth: int = 8, sub_queue: int = 8,
                 evict_after: int = 32, keyframe_interval: int = 16,
                 delta_tile: int = 32, codec_threads: int = 2,
                 lineage: bool = False,
                 abr_config: Optional[BroadcastAbrConfig] = None):
        self.audit_wire = audit_wire
        self.chaos = chaos
        self.lineage = lineage
        self.abr_config = abr_config or BroadcastAbrConfig()
        self._channel_kw = dict(
            ingest_depth=ingest_depth, keyframe_interval=keyframe_interval,
            delta_tile=delta_tile, codec_threads=codec_threads,
            sub_queue=sub_queue, evict_after=evict_after,
            audit_wire=audit_wire, chaos=chaos, lineage=lineage)
        self._channels: Dict[str, Channel] = {}
        self._relays: Dict[str, RelayNode] = {}
        self._relay_seq = 0
        self._lock = threading.Lock()
        self._closed_totals = {k: 0 for k in _FLOOR_KEYS}
        self._stopped = False

    # -- publish ---------------------------------------------------------

    def publish(self, name: str, publisher: str = "",
                tiers: Sequence[Union[Tier, str]] = ()) -> Channel:
        tiers = [Tier.parse(t) if isinstance(t, str) else t for t in tiers]
        with self._lock:
            if self._stopped:
                raise RuntimeError("broadcast plane is stopped")
            if name in self._channels:
                raise ValueError(f"channel {name!r} is already published "
                                 f"(one publisher per channel)")
            ch = Channel(name, publisher=publisher, tiers=tiers,
                         **self._channel_kw)
            self._channels[name] = ch
            return ch

    def channel(self, name: str) -> Channel:
        with self._lock:
            ch = self._channels.get(name)
        if ch is None:
            raise KeyError(f"no published channel {name!r} "
                           f"(live: {sorted(self._channels)})")
        return ch

    def tap(self, name: str):
        """The publisher-session hook: a callable the session's delivery
        loop invokes per delivered frame (serve.session.StreamSession
        ``tap``)."""
        return self.channel(name).offer

    def unpublish(self, name: str, timeout: float = 5.0) -> None:
        with self._lock:
            ch = self._channels.pop(name, None)
        if ch is None:
            return
        ch.flush(timeout=min(1.0, timeout))
        self._absorb_channel(ch)
        ch.close(timeout=timeout)

    # -- subscribe -------------------------------------------------------

    def subscribe(self, channel: str, tier: Union[Tier, str, None] = None,
                  queue_size: Optional[int] = None, abr: bool = False,
                  sub_id: Optional[str] = None) -> Subscription:
        if isinstance(tier, str):
            tier = Tier.parse(tier)
        controller = SubscriberAbr(self.abr_config) if abr else None
        return self.channel(channel).subscribe(
            tier=tier, queue_size=queue_size, abr=controller, sub_id=sub_id)

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            ch = self._channels.get(sub.channel)
        if ch is not None:
            ch.unsubscribe(sub)

    # -- relays ----------------------------------------------------------

    def spawn_relay(self, channel: str,
                    source_tier: Union[Tier, str, None] = None,
                    tiers: Sequence[Union[Tier, str]] = (),
                    chaos: Any = None, relay_id: Optional[str] = None,
                    upstream: Optional["BroadcastPlane"] = None,
                    **relay_kw) -> RelayNode:
        """Grow an egress replica off ``channel``. ``upstream`` defaults
        to THIS plane (the device box fans out to its own relays); a
        relay-only host passes the remote/front plane it subscribes
        through. ``chaos`` arms the corrupt-the-hop flip."""
        up = upstream or self
        if isinstance(source_tier, str):
            source_tier = Tier.parse(source_tier)
        if source_tier is None:
            ladder = up.channel(channel).ladder()
            if not ladder:
                raise ValueError(f"channel {channel!r} has no tiers to relay")
            source_tier = ladder[0]
        tiers = [Tier.parse(t) if isinstance(t, str) else t for t in tiers]
        with self._lock:
            if relay_id is None:
                relay_id = f"relay-{self._relay_seq}"
                self._relay_seq += 1
            if relay_id in self._relays:
                raise ValueError(f"relay {relay_id!r} already live")
        node = RelayNode(relay_id, up, channel, source_tier, tiers=tiers,
                         chaos=chaos, **relay_kw)
        node._upstream_plane = up
        with self._lock:
            self._relays[relay_id] = node
        return node

    def retire_relay(self, relay_id: str, timeout: float = 5.0) -> bool:
        with self._lock:
            node = self._relays.pop(relay_id, None)
        if node is None:
            return False
        self._absorb_relay(node)
        node.close(upstream=getattr(node, "_upstream_plane", None),
                   timeout=timeout)
        return True

    def relay(self, relay_id: str) -> RelayNode:
        with self._lock:
            return self._relays[relay_id]

    def relay_count(self) -> int:
        with self._lock:
            return len(self._relays)

    # -- lifetime floors -------------------------------------------------

    def _absorb_channel(self, ch: Channel) -> None:
        """Fold a closing channel's totals into the monotone floor —
        read BEFORE close() (close unsubscribes everyone, and the
        still-attached subscribers count as churn here)."""
        row = ch.stats()
        t = self._closed_totals
        t["ingest_dropped"] += row["ingest_dropped_total"]
        for lane in row["tiers"].values():
            t["encodes"] += lane["encodes_total"]
            t["fanout_frames"] += lane["fanout_frames_total"]
            t["delivered"] += lane["delivered_total"]
            t["dropped"] += lane["dropped_total"]
            t["churned_subscribers"] += (lane["churned_subscribers_total"]
                                         + lane["subscriber_count"])
            t["evicted_subscribers"] += lane["evicted_subscribers_total"]
            t["keyframes_forced"] += lane["keyframes_forced_total"]

    def _absorb_relay(self, node: RelayNode) -> None:
        row = node.stats()
        t = self._closed_totals
        t["relayed"] += row["relayed_total"]
        t["relay_corrupted_on_hop"] += row["corrupted_on_hop_total"]
        fwd = row["forward"]
        t["relay_forwarded"] += fwd["forwarded_total"]
        t["delivered"] += fwd["delivered_total"]
        t["dropped"] += fwd["dropped_total"]
        t["churned_subscribers"] += (fwd["churned_subscribers_total"]
                                     + fwd["subscriber_count"])
        t["evicted_subscribers"] += fwd["evicted_subscribers_total"]
        for lane in row.get("tiers", {}).values():
            t["encodes"] += lane["encodes_total"]
            t["fanout_frames"] += lane["fanout_frames_total"]
            t["delivered"] += lane["delivered_total"]
            t["dropped"] += lane["dropped_total"]
            t["churned_subscribers"] += (lane["churned_subscribers_total"]
                                         + lane["subscriber_count"])
            t["evicted_subscribers"] += lane["evicted_subscribers_total"]
            t["keyframes_forced"] += lane["keyframes_forced_total"]

    # -- observability ---------------------------------------------------

    def signals(self) -> Dict[str, float]:
        """Flat scrape series. Gauges reflect live state; ``*_total``
        counters are lifetime-monotone: the floor (closed channels /
        relays / churned subscribers) plus every live object's count."""
        with self._lock:
            channels = list(self._channels.values())
            relays = list(self._relays.values())
            floor = dict(self._closed_totals)
        subs = tiers = depth = 0
        enc = fan = deliv = drop = ingest_drop = churn = evic = keys = 0
        for ch in channels:
            row = ch.stats()
            ingest_drop += row["ingest_dropped_total"]
            for lane in row["tiers"].values():
                tiers += 1
                subs += lane["subscriber_count"]
                depth += lane["queue_depth"]
                enc += lane["encodes_total"]
                fan += lane["fanout_frames_total"]
                deliv += lane["delivered_total"]
                drop += lane["dropped_total"]
                churn += lane["churned_subscribers_total"]
                evic += lane["evicted_subscribers_total"]
                keys += lane["keyframes_forced_total"]
        relayed = fwd = hop_corrupt = 0
        for node in relays:
            row = node.stats()
            relayed += row["relayed_total"]
            hop_corrupt += row["corrupted_on_hop_total"]
            f = row["forward"]
            fwd += f["forwarded_total"]
            subs += f["subscriber_count"]
            deliv += f["delivered_total"]
            drop += f["dropped_total"]
            churn += f["churned_subscribers_total"]
            evic += f["evicted_subscribers_total"]
            for lane in row.get("tiers", {}).values():
                tiers += 1
                subs += lane["subscriber_count"]
                enc += lane["encodes_total"]
                deliv += lane["delivered_total"]
                drop += lane["dropped_total"]
                churn += lane["churned_subscribers_total"]
                evic += lane["evicted_subscribers_total"]
        return {
            "broadcast_channels": float(len(channels)),
            "broadcast_tiers": float(tiers),
            "broadcast_relays": float(len(relays)),
            "broadcast_subscribers": float(subs),
            "broadcast_queue_depth": float(depth),
            "broadcast_encodes_total": float(floor["encodes"] + enc),
            "broadcast_fanout_frames_total": float(
                floor["fanout_frames"] + fan),
            "broadcast_delivered_total": float(floor["delivered"] + deliv),
            "broadcast_dropped_total": float(floor["dropped"] + drop),
            "broadcast_ingest_dropped_total": float(
                floor["ingest_dropped"] + ingest_drop),
            "broadcast_churned_subscribers_total": float(
                floor["churned_subscribers"] + churn),
            "broadcast_evicted_subscribers_total": float(
                floor["evicted_subscribers"] + evic),
            "broadcast_keyframes_forced_total": float(
                floor["keyframes_forced"] + keys),
            "broadcast_relayed_total": float(floor["relayed"] + relayed),
            "broadcast_relay_forwarded_total": float(
                floor["relay_forwarded"] + fwd),
            "broadcast_relay_corrupted_on_hop_total": float(
                floor["relay_corrupted_on_hop"] + hop_corrupt),
        }

    def stats(self) -> dict:
        with self._lock:
            channels = dict(self._channels)
            relays = dict(self._relays)
        return {
            "channels": {n: ch.stats() for n, ch in channels.items()},
            "relays": {r: node.stats() for r, node in relays.items()},
            "channel_count": len(channels),
            "relay_count": len(relays),
        }

    # -- lifecycle -------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            relays = list(self._relays)
            channels = list(self._channels)
        for rid in relays:
            self.retire_relay(rid, timeout=timeout)
        for name in channels:
            self.unpublish(name, timeout=timeout)


# ---------------------------------------------------------------------------
# ZMQ gate: the remote-subscriber front door
# ---------------------------------------------------------------------------


class ZmqBroadcastGate:
    """One ROUTER socket remote watchers attach through.

    Protocol (client side is ``dvf_tpu subscribe``): a DEALER connects
    and sends one JSON hello ``{"op": "hello", "channel": c,
    "tier": spec, "queue": n}``; the gate registers a plane
    subscription and replies with the tier's wire config (the client
    needs the codec parameters + whether payloads are audit-stamped).
    From then on the gate's server thread drains that subscription's
    drop-oldest queue and ships ``[header-json, payload]`` pairs.
    Sends are non-blocking: a peer whose socket buffer is full drops
    frames at the gate (counted), and one that stops reading entirely
    is evicted by the lane like any local subscriber — remote watchers
    get the exact isolation contract local ones do. ``{"op": "bye"}``
    detaches.

    Liveness (resilience.continuity): ``{"op": "hb"}`` is answered with
    a pong, and EVERY control message beats the sender's liveness
    clock. With ``liveness_timeout_s > 0`` the serve loop reaps
    subscribers silent beyond the timeout — a watcher that vanished
    without a bye (crash, partition) stops pinning a lane slot and is
    counted as a partition instead of lingering forever. 0 keeps the
    legacy posture (eviction by send-pressure only)."""

    def __init__(self, plane: BroadcastPlane, endpoint: str,
                 name: str = "gate", liveness_timeout_s: float = 0.0):
        import zmq

        self._zmq = zmq
        self.plane = plane
        self.name = name
        self.closed = False
        self.send_drops = 0
        self.hellos = 0
        self.continuity = ContinuityStats()
        self._liveness = (LivenessMonitor(liveness_timeout_s)
                          if liveness_timeout_s > 0 else None)
        self._subs: Dict[bytes, Subscription] = {}
        self._lock = threading.Lock()
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.linger = 0
        self._sock.bind(endpoint)
        self.endpoint = endpoint
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve_loop, name=f"dvf-bcast-gate-{name}",
            daemon=True)
        self._thread.start()
        _LIVE_GATES.add(self)

    def _handle_hello(self, ident: bytes, msg: dict) -> None:
        ch = self.plane.channel(msg["channel"])
        tier = Tier.parse(msg["tier"]) if msg.get("tier") else None
        sub = self.plane.subscribe(
            msg["channel"], tier=tier, queue_size=msg.get("queue"),
            abr=bool(msg.get("abr")))
        with self._lock:
            self._subs[ident] = sub
        self.hellos += 1
        t = sub.tier
        meta = {"ok": True, "sub": sub.id, "tier": t.label(),
                "wire": t.wire, "quality": t.quality,
                "geometry": t.geometry, "audit": ch.audit_wire,
                "keyframe_interval": ch._lane_kw["keyframe_interval"],
                "delta_tile": ch._lane_kw["delta_tile"]}
        self._sock.send_multipart(
            [ident, json.dumps(meta).encode()], flags=self._zmq.NOBLOCK)

    def _serve_loop(self) -> None:
        zmq = self._zmq
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        while not self._stop.is_set():
            for _ in range(16):  # drain control traffic first
                if not poller.poll(0):
                    break
                parts = self._sock.recv_multipart()
                ident, body = parts[0], parts[-1]
                try:
                    if self._liveness is not None:
                        self._liveness.beat(ident)
                    msg = json.loads(body)
                    if msg.get("op") == "hello":
                        self._handle_hello(ident, msg)
                    elif msg.get("op") == "hb":
                        # Heartbeat pong: the quiet-link liveness beat
                        # (data frames also count — the client only
                        # needs hb when it is not being shipped frames).
                        self.continuity.inc("heartbeats")
                        self._sock.send_multipart(
                            [ident, json.dumps(
                                {"ok": True, "op": "hb"}).encode()],
                            flags=zmq.NOBLOCK)
                    elif msg.get("op") == "bye":
                        with self._lock:
                            sub = self._subs.pop(ident, None)
                        if self._liveness is not None:
                            self._liveness.forget(ident)
                        if sub is not None:
                            self.plane.unsubscribe(sub)
                except Exception as e:  # noqa: BLE001 — one bad peer
                    try:
                        self._sock.send_multipart(
                            [ident, json.dumps(
                                {"ok": False, "error": repr(e)}).encode()],
                            flags=zmq.NOBLOCK)
                    except zmq.ZMQError:
                        pass
            with self._lock:
                live = list(self._subs.items())
            shipped = 0
            for ident, sub in live:
                if sub.evicted:
                    with self._lock:
                        self._subs.pop(ident, None)
                    continue
                for d in sub.poll(16):
                    head = json.dumps({
                        "seq": d.seq, "ts": d.capture_ts,
                        "key": bool(d.keyframe)}).encode()
                    try:
                        self._sock.send_multipart(
                            [ident, head, d.payload], flags=zmq.NOBLOCK)
                        shipped += 1
                    except zmq.ZMQError:
                        self.send_drops += 1
            if self._liveness is not None:
                # Reap watchers silent beyond the liveness timeout: a
                # peer that crashed (or partitioned) without a bye must
                # not pin its lane slot until send-pressure eviction
                # happens to notice. Clients of an armed gate beat with
                # {"op": "hb"} — receiving frames is not proof the peer
                # still exists (ROUTER sends never block on a ghost).
                for ident in self._liveness.dead():
                    self._liveness.forget(ident)
                    with self._lock:
                        sub = self._subs.pop(ident, None)
                    if sub is not None:
                        self.plane.unsubscribe(sub)
                        self.continuity.inc("partitions")
            if not shipped:
                self._stop.wait(0.005)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._subs)
        return {"endpoint": self.endpoint, "remote_subscribers": n,
                "hellos_total": self.hellos,
                "send_drops_total": self.send_drops,
                "continuity": self.continuity.summary()}

    def close(self, timeout: float = 5.0) -> None:
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        self._thread.join(timeout=timeout)
        with self._lock:
            subs = list(self._subs.values())
            self._subs.clear()
        for sub in subs:
            self.plane.unsubscribe(sub)
        self._sock.close(0)
        _LIVE_GATES.discard(self)
