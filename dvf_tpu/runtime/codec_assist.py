"""On-device codec assist: the transform half of the host JPEG cycle,
moved onto the accelerator.

Three device stages, all appended AFTER the filter program on the result
batch (they consume the engine's output exactly where the egress plane
fetches it, so their cost hides under the next batch's staging the same
way the per-shard D2H does — the GPUOS operation-fusion discipline,
PAPERS.md arXiv:2604.17861, applied at the codec boundary):

- :class:`DeviceDeltaProbe` — the temporal-delta wire's change
  detection: per-tile max-abs-diff of each output frame against the
  previously delivered one (``ops.pallas_kernels.tile_maxdiff`` — a
  Pallas kernel on aligned geometries, the jnp golden elsewhere).
  Within a batch, frame *i*'s predecessor is row *i−1*; across batches
  the probe keeps the last delivered row as device-resident state. The
  host fetches a few-hundred-byte bitmap instead of running its own
  frame-sized reduction pass (``transport.codec.host_tile_maxdiff``).
- :class:`DeviceCodecAssist` — RGB→YCbCr (BT.601 full range, libjpeg's
  matrix) plus the 2×2 chroma subsample on device, so the host codec
  starts from HALF the bytes and skips its color-convert and
  downsample passes entirely: ``NativeJpegCodec.encode_ycbcr420`` runs
  DCT + quantization + entropy coding only (jpeg_write_raw_data).
- :class:`FusedDeltaTransform` — the codec endgame: probe AND convert
  AND per-8×8-block forward DCT AND quantization fused into ONE jitted
  program per batch. Only dirty tiles' int16 coefficient blocks and the
  bitmap cross D2H; the host runs entropy coding and nothing else
  (``NativeJpegCodec.encode_coefficients``, jpeg_write_coefficients).

All are separate tiny jitted programs rather than a re-trace of the
filter step: jax's async dispatch queues them back-to-back with the
filter program (no host sync in between), the engine's compiled
signature and every egress consumer stay untouched, and a path that
doesn't want the stage never pays for it.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dvf_tpu.ops.pallas_kernels import tile_maxdiff


class DeviceDeltaProbe:
    """Device-side dirty-tile bitmaps for a SEQUENTIAL frame stream.

    ``bitmaps(batch)`` returns a host ``(B, ⌈H/tile⌉, ⌈W/tile⌉)`` uint8
    array of per-tile max-abs-diffs vs each frame's predecessor. Only
    valid for streams whose batch rows are consecutive frames of ONE
    stream (pipeline, ZMQ worker) — a cross-session serve batch
    interleaves tenants, whose codecs fall back to the host reduction.

    Reference semantics: the probe diffs each frame against its
    PREDECESSOR, not against the encoder's last-shipped state. At
    ``delta_threshold=0`` (the default) the two are exactly equivalent —
    every change ships the moment it happens, so "changed since the
    previous frame" and "changed since last shipped" select the same
    tiles. At thresholds > 0 they differ: sub-threshold drift that the
    closed-loop host reduction re-sends once cumulative divergence
    crosses the threshold stays invisible to a per-frame diff, so drift
    is bounded only by the keyframe cadence — use the host path (no
    bitmap) for lossy thresholds.

    The first call's row 0 has no predecessor and is marked all-dirty
    (the delta codec encodes a keyframe there anyway — no encoder
    reference — so the conservative answer costs nothing). If a batch is
    dropped AFTER the probe ran (downstream containment), the next
    batch diffs against the dropped batch's tail — under-reporting
    changes until the next keyframe bounds the staleness, exactly like
    any lost delta frame.
    """

    def __init__(self, tile: int = 32):
        import jax

        self.tile = int(tile)
        self._prev = None  # (1, H, W, C) device array — last delivered row
        self._shape: Optional[Tuple[int, ...]] = None

        def probe(batch, prev):
            chain = jax.numpy.concatenate([prev, batch[:-1]], axis=0)
            return tile_maxdiff(batch, chain, self.tile), batch[-1:]

        self._fn = jax.jit(probe)

    def bitmaps(self, batch) -> np.ndarray:
        """One device reduction + a tiny host fetch; ``batch`` is the
        engine's (possibly sharded) result array."""
        shape = tuple(batch.shape)
        if self._prev is None or self._shape != shape:
            # First batch: rows 1.. still have in-batch predecessors —
            # only row 0 lacks one and is marked all-dirty (the delta
            # encoder keyframes it anyway, having no reference). Marking
            # the WHOLE batch dirty would make the device path ship
            # every tile raw for rows 1.., silently diverging from the
            # host-detection path's output.
            self._shape = shape
            tiles, self._prev = self._fn(batch, batch[:1])
            out = np.array(tiles)  # own the buffer: jax arrays view
            #   read-only and row 0 is overwritten below
            out[0] = 255
            return out
        tiles, self._prev = self._fn(batch, self._prev)
        return np.asarray(tiles)

    def reset(self) -> None:
        """Drop the device state (geometry change, engine rebuild)."""
        self._prev = None
        self._shape = None


# -- YCbCr 4:2:0 device stages ------------------------------------------

# BT.601 full-range (JFIF) — the same matrix libjpeg applies on the host
# path this stage replaces, so assist output decodes indistinguishably.
_RGB2Y = (0.299, 0.587, 0.114)
_RGB2CB = (-0.168735892, -0.331264108, 0.5)
_RGB2CR = (0.5, -0.418687589, -0.081312411)


def rgb_to_ycbcr420(batch):
    """Device stage: (B, H, W, 3) uint8 RGB → (y, cb, cr) uint8 planes
    ((B, H, W), (B, H/2, W/2), (B, H/2, W/2)). Odd H/W are edge-padded
    to even first (mirrors libjpeg's own edge replication). The chroma
    subsample is the 2×2 mean — what libjpeg's default h2v2 downsampler
    computes."""
    import jax.numpy as jnp

    b, h, w, _ = batch.shape
    if h % 2 or w % 2:
        batch = jnp.pad(batch, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)),
                        mode="edge")
        h, w = h + h % 2, w + w % 2
    x = batch.astype(jnp.float32)
    r, g, bl = x[..., 0], x[..., 1], x[..., 2]
    y = _RGB2Y[0] * r + _RGB2Y[1] * g + _RGB2Y[2] * bl
    cb = 128.0 + _RGB2CB[0] * r + _RGB2CB[1] * g + _RGB2CB[2] * bl
    cr = 128.0 + _RGB2CR[0] * r + _RGB2CR[1] * g + _RGB2CR[2] * bl
    cb = cb.reshape(b, h // 2, 2, w // 2, 2).mean(axis=(2, 4))
    cr = cr.reshape(b, h // 2, 2, w // 2, 2).mean(axis=(2, 4))
    to_u8 = lambda p: jnp.clip(jnp.round(p), 0, 255).astype(jnp.uint8)  # noqa: E731
    return to_u8(y), to_u8(cb), to_u8(cr)


def ycbcr420_to_rgb_host(y: np.ndarray, cb: np.ndarray,
                         cr: np.ndarray) -> np.ndarray:
    """Host inverse (tests + any raw-assist wire consumer): nearest
    chroma upsample + BT.601 inverse, back to (…, H, W, 3) uint8."""
    yf = y.astype(np.float32)
    cbf = np.repeat(np.repeat(cb.astype(np.float32) - 128.0, 2, axis=-2),
                    2, axis=-1)
    crf = np.repeat(np.repeat(cr.astype(np.float32) - 128.0, 2, axis=-2),
                    2, axis=-1)
    r = yf + 1.402 * crf
    g = yf - 0.344136286 * cbf - 0.714136286 * crf
    b = yf + 1.772 * cbf
    return np.clip(np.round(np.stack([r, g, b], axis=-1)), 0,
                   255).astype(np.uint8)


class DeviceCodecAssist:
    """jit-compiled RGB→YCbCr420 stage + host plane fetch.

    ``planes(batch)`` runs the conversion on device (queued behind the
    filter program by async dispatch) and materializes the three planes
    on the host — 1.5 bytes/px instead of 3, which is both the D2H and
    the host-codec input saving. Feed the per-frame planes to
    ``NativeJpegCodec.encode_ycbcr420`` for the entropy-only encode.
    """

    def __init__(self):
        import jax

        self._fn = jax.jit(rgb_to_ycbcr420)

    def planes(self, batch):
        y, cb, cr = self._fn(batch)
        return np.asarray(y), np.asarray(cb), np.asarray(cr)


# -- full-transform assist: probe + convert + DCT + quant, ONE pass -----


class FusedDeltaTransform:
    """The codec endgame's device stage: dirty-tile probe, RGB→YCbCr
    4:2:0, per-8×8-block forward DCT, and quantization as ONE fused
    jitted program per batch (``ops.pallas_kernels.dct8x8_quant`` beside
    ``tile_maxdiff``, inside a single jit — XLA schedules the whole
    chain as one dispatch; ``calls`` counts dispatches so tests can pin
    the one-dispatch-per-batch property). The host never sees pixels:
    only dirty tiles' int16 coefficient blocks and the few-hundred-byte
    bitmap cross D2H (``transport.codec.CoefficientFrame`` slices lazily),
    and ``NativeJpegCodec.encode_coefficients`` does entropy coding and
    nothing else.

    Coefficients come out GROUPED BY DELTA TILE — y (B, nty, ntx, t/8,
    t/8, 8, 8), cb/cr (B, nty, ntx, t/16, t/16, 8, 8) — so one dirty
    tile is one contiguous slice. That forces ``tile % 16 == 0`` (chroma
    blocks must not straddle tiles) and H, W multiples of the tile; gate
    with :meth:`supports` and fall back to :class:`DeviceDeltaProbe` +
    host encode elsewhere (e.g. 1080p, where H = 1080 isn't a multiple
    of 32).

    Probe semantics are identical to :class:`DeviceDeltaProbe` (same
    ``tile_maxdiff``, same predecessor chaining, same all-dirty first
    row) — at ``delta_threshold=0`` the dirty-tile SELECTION is
    bit-identical to the host path's, which tests pin.
    """

    def __init__(self, tile: int = 32, quality: int = 90):
        import jax
        import jax.numpy as jnp

        from dvf_tpu.ops.pallas_kernels import dct8x8_quant, jpeg_quant_table

        if tile % 16:
            raise ValueError(f"fused transform needs tile % 16 == 0 "
                             f"(chroma blocks must tile), got {tile}")
        self.tile = int(tile)
        self.quality = int(quality)
        self.calls = 0  # fused device dispatches (== batches processed)
        self._prev = None
        self._shape: Optional[Tuple[int, ...]] = None
        ql = jpeg_quant_table(quality)
        qc = jpeg_quant_table(quality, chroma=True)
        t = self.tile

        def group(q, bt):
            # raster blocks (B, nby, nbx, 8, 8) → per-delta-tile
            # (B, nty, ntx, bt, bt, 8, 8)
            b, nby, nbx = q.shape[0], q.shape[1], q.shape[2]
            return (q.reshape(b, nby // bt, bt, nbx // bt, bt, 8, 8)
                    .transpose(0, 1, 3, 2, 4, 5, 6))

        def fused(batch, prev):
            chain = jnp.concatenate([prev, batch[:-1]], axis=0)
            tiles = tile_maxdiff(batch, chain, t)
            y, cb, cr = rgb_to_ycbcr420(batch)
            yq = group(dct8x8_quant(y, ql), t // 8)
            cbq = group(dct8x8_quant(cb, qc), t // 16)
            crq = group(dct8x8_quant(cr, qc), t // 16)
            return tiles, yq, cbq, crq, batch[-1:]

        self._fn = jax.jit(fused)

    @staticmethod
    def supports(shape, tile: int) -> bool:
        """Whether this batch geometry can take the fused path: (B, H,
        W, 3) with H and W multiples of a tile that is itself a multiple
        of 16."""
        if len(shape) != 4 or shape[3] != 3:
            return False
        h, w = shape[1], shape[2]
        return tile % 16 == 0 and h % tile == 0 and w % tile == 0

    def process(self, batch):
        """One fused dispatch → ``(bitmaps, coefficient_frames)``: a
        host (B, nty, ntx) uint8 bitmap array and one lazy
        :class:`~dvf_tpu.transport.codec.CoefficientFrame` per row
        (nothing frame-sized crosses D2H here — the codec fetches dirty
        tiles' blocks on demand)."""
        from dvf_tpu.transport.codec import CoefficientFrame

        shape = tuple(batch.shape)
        if not self.supports(shape, self.tile):
            raise ValueError(f"geometry {shape} unsupported at tile "
                             f"{self.tile} (use supports() to gate)")
        if self._prev is None or self._shape != shape:
            # First batch: same semantics as DeviceDeltaProbe — only
            # row 0 lacks a predecessor and is marked all-dirty.
            self._shape = shape
            tiles, yq, cbq, crq, self._prev = self._fn(batch, batch[:1])
            self.calls += 1
            bm = np.array(tiles)
            bm[0] = 255
        else:
            tiles, yq, cbq, crq, self._prev = self._fn(batch, self._prev)
            self.calls += 1
            bm = np.asarray(tiles)
        h, w = shape[1], shape[2]
        frames = [CoefficientFrame(yq[i], cbq[i], crq[i], h, w, self.tile,
                                   self.quality)
                  for i in range(shape[0])]
        return bm, frames

    def reset(self) -> None:
        """Drop the device state (geometry change, engine rebuild)."""
        self._prev = None
        self._shape = None
