"""Streamed shard-level batch ingest: overlap decode, H2D, and compute.

The BENCH_r05 stage decomposition showed the hot path is ingest-bound,
not compute-bound: the pipeline assembled the ENTIRE batch on the host
(decode-all → stage-all) and then shipped it as one monolithic,
serializing ``device_put`` before compute could start — host staging up
to 3.5 ms/batch and H2D 3.7–7.0 ms against 0.6–1.2 ms of per-frame
compute, with the link at 13% of its roofline. This module closes that
gap with the classic decoupled access-execute / latency-hiding move
(TVM, arXiv:1802.04799): frames decode directly into *per-device-shard*
staging slabs, and each shard is ``device_put`` the moment its rows fill,
so the H2D of shard *i* overlaps the decode of shard *i+1* and the device
compute of batch *k−1*. The finished batch is assembled with
``jax.make_array_from_single_device_arrays`` and handed to
``Engine.submit_resident`` — the engine's internal ``device_put`` is
skipped entirely.

Timeline, monolithic vs streamed (one batch of 4 shards):

    monolithic   decode ████████████ → H2D ████████ → compute ████
    streamed     decode ███░███░███░███░
                 H2D       ████ ████ ████ ████          (per shard,
                 compute ░░░░ batch k−1 ░░░░░░░         overlapped)

Shard granularity follows the engine's input sharding:

- the batch axis is partitioned over devices (data DP) → one slab per
  device batch-shard, sub-chunked up to ``depth`` pieces so transfers
  start before a whole shard decodes (a single-device mesh streams the
  same way: ``depth`` row-chunks concatenated on device — one cheap HBM
  copy buys the host↔device overlap);
- H additionally sharded (space axis) → per-device slabs carry that
  device's H slice; a decoded frame scatters its H slices across slabs;
- any *replicated* placement (batch smaller than the data axis, a model
  axis, an explicitly replicated spec) falls back to the monolithic
  whole-batch ``device_put``: XLA broadcasts a replicated transfer
  device-side, which per-device host puts cannot beat. The effective
  mode is recorded in the ingest stats either way.

Slot discipline is the pipeline's staging-pool contract unchanged: the
caller provides a monotonically increasing slot id per batch and
guarantees (via its in-flight bound) that a slot is only revisited after
its batch has been collected — by which point the device step has
consumed the slabs, so rewriting them is safe even if the backend
aliased host memory.

``depth`` is the dispatch-depth knob (``--ingest-depth``): how many
shard transfers may be in flight before the assembler blocks on the
oldest — bounding both the host memory pinned by outstanding transfers
and the burstiness of the H2D queue.
"""

from __future__ import annotations

import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dvf_tpu.obs.metrics import IngestStats
from dvf_tpu.obs.trace import INGEST_H2D, INGEST_OVERLAP, INGEST_STAGE
from dvf_tpu.resilience.faults import FaultError, FaultKind

INGEST_MODES = ("streamed", "monolithic")

# Below this calibrated blocking-put cost (Engine.h2d_block_ms, measured
# at compile), the fixed per-batch streaming overhead — shard-put
# dispatches, the on-device chunk concat, mesh-array assembly — exceeds
# anything overlap can hide, so the assembler stays monolithic (measured
# on the CPU backend: 128×128×8 streams at 480 fps vs 2507 monolithic
# because the whole blocking put costs ~0.1 ms). A 1080p batch on any
# real link clears this easily (3–8 ms on PCIe, hundreds on the bench
# tunnel). Tests that exercise the streaming machinery at tiny sizes
# monkeypatch this to 0.
MIN_STREAM_H2D_MS = 2.0

# Host-slab accounting registry (obs.memory): every live assembler is
# weakly tracked so the scrape-time gauges — and the conftest
# session-end guard asserting zero OCCUPIED slabs once every owner has
# closed — can walk host staging memory without owners wiring anything.
_LIVE_ASSEMBLERS: "weakref.WeakSet" = weakref.WeakSet()


def live_assemblers() -> List["ShardedBatchAssembler"]:
    """Every assembler still referenced anywhere in the process (a
    released one stays listed but reports 0 ``slab_bytes``)."""
    return list(_LIVE_ASSEMBLERS)


def occupied_slab_bytes() -> int:
    """Total host staging bytes currently pinned by live assemblers —
    the ingest half of ``dvf_mem_host_slab_bytes``."""
    return sum(a.slab_bytes() for a in live_assemblers())


def _span(slc: slice, dim: int) -> Tuple[int, int]:
    start, stop, step = slc.indices(dim)
    if step != 1:
        raise ValueError(f"non-unit stride in shard index: {slc}")
    return start, stop


class _Chunk:
    """One contiguous row range of the batch and its per-tail slabs.

    ``tails`` maps a hashable key (the shard's H/W/C index) to the numpy
    slice tuple selecting that portion of a frame; ``targets`` lists the
    (device, tail_key) puts this chunk owes. Slabs live per *slot* (the
    caller's staging-pool index) so an in-flight chunk is never rewritten.
    """

    __slots__ = ("start", "stop", "tails", "targets", "slabs", "frame_like")

    def __init__(self, start: int, stop: int):
        self.start = start
        self.stop = stop
        self.tails: Dict[tuple, tuple] = {}
        self.targets: List[Tuple[Any, tuple]] = []
        self.slabs: List[Dict[tuple, np.ndarray]] = []  # per slot
        self.frame_like = False  # single tail covering the full frame

    @property
    def rows(self) -> int:
        return self.stop - self.start


class ShardedBatchAssembler:
    """Stages batches into per-shard slabs and streams them to devices.

    One assembler per (batch signature, sharding); ``begin(slot)`` yields
    a :class:`BatchBuilder` for one batch. ``mode="monolithic"`` is the
    escape hatch (``--ingest=monolithic``): one whole-batch host buffer
    per slot, handed back for the engine's classic ``submit`` path —
    byte-for-byte the pre-streaming behavior.
    """

    def __init__(
        self,
        batch_shape: Tuple[int, ...],
        dtype,
        sharding=None,
        mode: str = "streamed",
        depth: int = 4,
        slots: int = 5,
        tracer=None,
        track: int = 0,
        stats: Optional[IngestStats] = None,
        chaos=None,
    ):
        if mode not in INGEST_MODES:
            raise ValueError(f"ingest mode must be one of {INGEST_MODES}, "
                             f"got {mode!r}")
        if depth < 1:
            raise ValueError("ingest depth must be >= 1")
        self.batch_shape = tuple(batch_shape)
        self.dtype = np.dtype(dtype)
        self.sharding = sharding
        self.mode = mode
        self.depth = depth
        self.slots = max(1, slots)
        self.tracer = tracer
        self.track = track
        self.chaos = chaos  # resilience.chaos.FaultPlan — the "h2d"
        #   injection site fires per shard put when armed (None = zero
        #   overhead)
        self.stats = stats if stats is not None else IngestStats(
            requested_mode=mode, depth=depth)
        self._chunks: List[_Chunk] = []
        self._chunk_of_row: List[int] = []
        self._device_order: List[Any] = []
        self._mono_pool: Optional[List[np.ndarray]] = None
        self._scratch: Optional[np.ndarray] = None  # general-path decode buf
        self.effective_mode = self._plan()
        self.stats.effective_mode = self.effective_mode
        self.stats.pool_allocs += 1
        _LIVE_ASSEMBLERS.add(self)

    def slab_bytes(self) -> int:
        """Host staging memory this assembler currently pins (streamed
        shard slabs, the monolithic pool, the decode scratch) — 0 after
        :meth:`release`. The memory-accounting gauge's source."""
        total = 0
        for c in self._chunks:
            for slot in c.slabs:
                total += sum(a.nbytes for a in slot.values())
        if self._mono_pool is not None:
            total += sum(a.nbytes for a in self._mono_pool)
        if self._scratch is not None:
            total += self._scratch.nbytes
        return total

    # -- layout planning -------------------------------------------------

    def _plan(self) -> str:
        """Derive the chunk layout from the sharding; returns the mode
        actually used ("monolithic" when streaming cannot help)."""
        if self.mode == "monolithic" or self.sharding is None:
            return self._plan_monolithic()
        cal = self.stats.h2d_block_ms
        if cal is not None and cal < MIN_STREAM_H2D_MS:
            return self._plan_monolithic(reason="cheap_transfer")
        b = self.batch_shape[0]
        try:
            idx_map = self.sharding.devices_indices_map(self.batch_shape)
        except Exception:  # noqa: BLE001 — exotic sharding: stay correct
            return self._plan_monolithic(reason="unsupported_sharding")
        frame_shape = self.batch_shape[1:]
        groups: Dict[Tuple[int, int], List[tuple]] = {}
        try:
            for dev, idx in idx_map.items():
                b0, b1 = _span(idx[0], b)
                tail = tuple(idx[1:])
                key = tuple(_span(sl, dim)
                            for sl, dim in zip(tail, frame_shape))
                groups.setdefault((b0, b1), []).append((dev, tail, key))
        except ValueError:
            return self._plan_monolithic(reason="unsupported_sharding")
        ranges = sorted(groups)
        # The streamed path needs the device shards to PARTITION the batch
        # axis: contiguous non-overlapping row ranges covering [0, B), and
        # no two devices holding the same (rows, tail) portion. Any
        # replication means device_put's device-side broadcast beats
        # repeated host puts — monolithic wins there.
        if (ranges[0][0] != 0 or ranges[-1][1] != b
                or any(ranges[i][1] != ranges[i + 1][0]
                       for i in range(len(ranges) - 1))):
            return self._plan_monolithic(reason="replicated_layout")
        for members in groups.values():
            keys = [k for _, _, k in members]
            if len(keys) != len(set(keys)):
                return self._plan_monolithic(reason="replicated_layout")
        self._device_order = list(idx_map)
        for b0, b1 in ranges:
            rows = b1 - b0
            n_sub = min(self.depth, rows)
            bounds = [b0 + (rows * i) // n_sub for i in range(n_sub)] + [b1]
            for s, e in zip(bounds, bounds[1:]):
                c = _Chunk(s, e)
                for dev, tail, key in groups[(b0, b1)]:
                    c.tails[key] = tail
                    c.targets.append((dev, key))
                c.frame_like = (
                    len(c.tails) == 1
                    and next(iter(c.tails)) == tuple(
                        (0, d) for d in frame_shape))
                c.slabs = [
                    {key: np.empty(
                        (c.rows,) + tuple(stop - start
                                          for start, stop in key),
                        self.dtype)
                     for key in c.tails}
                    for _ in range(self.slots)
                ]
                self._chunks.append(c)
        self._chunk_of_row = [0] * b
        for i, c in enumerate(self._chunks):
            for r in range(c.start, c.stop):
                self._chunk_of_row[r] = i
        return "streamed"

    def _plan_monolithic(self, reason: Optional[str] = None) -> str:
        self.stats.fallback_reason = reason
        self._mono_pool = [
            np.empty(self.batch_shape, self.dtype) for _ in range(self.slots)
        ]
        return "monolithic"

    def _scratch_for(self, rows: int) -> np.ndarray:
        """Whole-frame decode scratch for the general (H-sharded) path —
        allocated once at the largest chunk size, reused every batch."""
        if self._scratch is None:
            biggest = max(c.rows for c in self._chunks)
            self._scratch = np.empty(
                (biggest,) + self.batch_shape[1:], self.dtype)
        return self._scratch[:rows]

    def begin(self, slot: int) -> "BatchBuilder":
        """Start staging one batch into the given staging-pool slot."""
        return BatchBuilder(self, slot % self.slots)

    def release(self) -> None:
        """Drop every staging buffer reference eagerly.

        For an assembler abandoned mid-batch (the ZMQ worker's geometry
        re-probe), the raising frame's traceback keeps the half-staged
        builder — and through it this assembler and all its slabs —
        alive for the whole retry, doubling peak staging memory until GC.
        Releasing explicitly caps the overlap at zero; in-flight
        ``device_put`` s keep their own references to the individual
        slabs they read, so dropping ours is always safe. The assembler
        is unusable afterwards (callers null their reference).
        """
        for c in self._chunks:
            c.slabs = []
        self._chunks = []
        self._chunk_of_row = []
        self._device_order = []
        self._mono_pool = None
        self._scratch = None


class BatchBuilder:
    """Mutable per-batch staging state; produced by ``begin``, consumed by
    ``finish``. Rows must be written in increasing order (the pipeline,
    batcher, and decode paths are all naturally monotonic)."""

    def __init__(self, asm: ShardedBatchAssembler, slot: int):
        self.asm = asm
        self.slot = slot
        self._streamed = asm.effective_mode == "streamed"
        self._filled = [0] * len(asm._chunks) if self._streamed else [0]
        self._parts: Dict[Any, List[Any]] = {d: [] for d in asm._device_order}
        self._inflight: List[List[Any]] = []
        self._stage_s = 0.0
        self._put_s = 0.0
        self._wait_s = 0.0
        self._first_put_t: Optional[float] = None
        self._t_begin = time.perf_counter()

    # -- row staging -----------------------------------------------------

    def write_row(self, row: int, frame: np.ndarray) -> None:
        """Copy one frame into its shard slab(s); launches a shard's H2D
        the moment its last row lands."""
        t0 = time.perf_counter()
        if not self._streamed:
            np.copyto(self._mono_buf()[row], frame)
            self._stage_s += time.perf_counter() - t0
            return
        ci = self.asm._chunk_of_row[row]
        c = self.asm._chunks[ci]
        local = row - c.start
        slabs = c.slabs[self.slot]
        for key, tail in c.tails.items():
            np.copyto(slabs[key][local], frame[tail])
        self._filled[ci] += 1
        self._stage_s += time.perf_counter() - t0
        if self._filled[ci] == c.rows:
            self._launch(ci)

    def windows(self, k: int) -> List[Tuple[int, int]]:
        """Contiguous row windows covering [0, k) for bulk decode — each
        window is one shard chunk (clipped at k), so committing a window
        launches its transfer while the next window decodes."""
        if not self._streamed:
            return [(0, k)] if k else []
        out = []
        for c in self.asm._chunks:
            if c.start >= k:
                break
            out.append((c.start, min(c.stop, k)))
        return out

    def window_view(self, start: int, stop: int) -> np.ndarray:
        """A (rows, H, W, C) buffer for rows [start, stop): the shard slab
        itself when it holds whole frames (zero-copy decode target), else
        a reused scratch that ``commit_window`` scatters into slabs."""
        if not self._streamed:
            return self._mono_buf()[start:stop]
        c = self.asm._chunks[self.asm._chunk_of_row[start]]
        if c.frame_like:
            key = next(iter(c.tails))
            return c.slabs[self.slot][key][start - c.start:stop - c.start]
        return self.asm._scratch_for(stop - start)

    def commit_window(self, start: int, stop: int) -> None:
        """Mark rows [start, stop) staged (scattering the scratch buffer
        into shard slabs if the fast path was unavailable); launches the
        chunk's transfers when it fills."""
        t0 = time.perf_counter()
        if not self._streamed:
            self._filled[0] = stop
            self._stage_s += time.perf_counter() - t0
            return
        ci = self.asm._chunk_of_row[start]
        c = self.asm._chunks[ci]
        if not c.frame_like:
            scratch = self.asm._scratch_for(stop - start)
            slabs = c.slabs[self.slot]
            for key, tail in c.tails.items():
                for i in range(stop - start):
                    np.copyto(slabs[key][start - c.start + i],
                              scratch[i][tail])
        self._filled[ci] += stop - start
        self._stage_s += time.perf_counter() - t0
        if self._filled[ci] == c.rows:
            self._launch(ci)

    # -- transfers -------------------------------------------------------

    def _launch(self, ci: int) -> None:
        import jax

        c = self.asm._chunks[ci]
        slabs = c.slabs[self.slot]
        if self.asm.chaos is not None:
            # Injection site "h2d": a delay rule stalls this put (models a
            # congested link), a raise rule denies it — either way exactly
            # where a real transfer fault would surface.
            self.asm.chaos.fire("h2d")
        t0 = time.perf_counter()
        if self._first_put_t is None:
            self._first_put_t = t0
        arrs = []
        try:
            for dev, key in c.targets:
                arr = jax.device_put(slabs[key], dev)
                self._parts[dev].append(arr)
                arrs.append(arr)
        except Exception as e:  # noqa: BLE001 — carry the fault kind so
            # containment classifies this as h2d (and can escalate to the
            # streamed→monolithic fallback) instead of guessing from site.
            raise FaultError(
                FaultKind.H2D,
                f"shard device_put failed for rows {c.start}:{c.stop}: "
                f"{e!r}") from e
        t1 = time.perf_counter()
        self._put_s += t1 - t0
        tracer = self.asm.tracer
        if tracer is not None and tracer.enabled:
            nbytes = sum(slabs[key].nbytes for _, key in c.targets)
            off = time.time() - time.perf_counter()  # monotonic → wall
            tracer.complete(INGEST_H2D, t0 + off, t1 + off, self.asm.track,
                            rows=f"{c.start}:{c.stop}", bytes=nbytes)
        self._inflight.append(arrs)
        if len(self._inflight) > self.asm.depth:
            oldest = self._inflight.pop(0)
            tw = time.perf_counter()
            for a in oldest:
                a.block_until_ready()
            self._wait_s += time.perf_counter() - tw

    def _mono_buf(self) -> np.ndarray:
        return self.asm._mono_pool[self.slot]

    # -- completion ------------------------------------------------------

    def finish(self, valid: int):
        """Pad rows [valid, B) by repeating the last valid row, flush the
        remaining shard transfers, and assemble the batch.

        Returns ``(batch, resident)``: a mesh-sharded ``jax.Array`` with
        ``resident=True`` on the streamed path (feed
        ``Engine.submit_resident``), or the host staging array with
        ``resident=False`` on the monolithic path (feed ``Engine.submit``,
        which owns the transfer exactly as before).
        """
        b = self.asm.batch_shape[0]
        if not (0 < valid <= b):
            raise ValueError(f"valid={valid} out of range for batch {b}")
        if not self._streamed:
            t0 = time.perf_counter()
            buf = self._mono_buf()
            for row in range(valid, b):
                np.copyto(buf[row], buf[valid - 1])
            self._stage_s += time.perf_counter() - t0
            self._record(time.perf_counter())
            return buf, False
        # Pad from the already-staged slabs: the source row's chunk may
        # be launched (its slab is only read), the destination rows are
        # by construction in not-yet-launched chunks.
        t0 = time.perf_counter()
        src_c = self.asm._chunks[self.asm._chunk_of_row[valid - 1]]
        src_local = valid - 1 - src_c.start
        for row in range(valid, b):
            ci = self.asm._chunk_of_row[row]
            c = self.asm._chunks[ci]
            slabs = c.slabs[self.slot]
            for key in c.tails:
                np.copyto(slabs[key][row - c.start],
                          src_c.slabs[self.slot][key][src_local])
            self._filled[ci] += 1
            if self._filled[ci] == c.rows:
                self._stage_s += time.perf_counter() - t0
                self._launch(ci)
                t0 = time.perf_counter()
        self._stage_s += time.perf_counter() - t0
        import jax
        import jax.numpy as jnp

        arrs = []
        for dev in self.asm._device_order:
            parts = self._parts[dev]
            arrs.append(parts[0] if len(parts) == 1
                        else jnp.concatenate(parts, axis=0))
        batch = jax.make_array_from_single_device_arrays(
            self.asm.batch_shape, self.asm.sharding, arrs)
        t_end = time.perf_counter()
        tracer = self.asm.tracer
        if tracer is not None and tracer.enabled and self._first_put_t:
            off = time.time() - time.perf_counter()  # monotonic → wall
            tracer.complete(INGEST_OVERLAP, self._first_put_t + off,
                            t_end + off, self.asm.track, valid=valid)
            tracer.complete(INGEST_STAGE, self._t_begin + off, t_end + off,
                            self.asm.track,
                            stage_ms=round(self._stage_s * 1e3, 3))
        self._record(t_end)
        return batch, True

    def _record(self, t_end: float) -> None:
        self.asm.stats.record_batch(
            stage_ms=self._stage_s * 1e3,
            put_ms=self._put_s * 1e3,
            wait_ms=self._wait_s * 1e3,
            span_ms=(t_end - self._t_begin) * 1e3,
        )
