"""Streamed shard-level egress + asynchronous codec plane.

The symmetric twin of :mod:`dvf_tpu.runtime.ingest`, on the D2H side.
PR 3 streamed the ingest half (decode → per-shard H2D overlapped with
compute), but every delivery path still blocked on a whole-batch
``np.asarray(result)`` — one serializing fetch that allocates a fresh
host batch — and only then encoded, serially. The measured head-to-head
pins the cost: same-codec throughput is 1.27× the reference while
raw-wire is 8.3× (benchmarks/REFERENCE_HEADTOHEAD.json) — the pipeline
is egress/codec-bound. This module closes that gap with the same
operation-overlap discipline, applied at delivery:

- :class:`ShardedBatchFetcher` — per-output-shard ``copy_to_host_async``
  issued the moment the batch is submitted (so D2H runs under the tail
  of compute and the next batch's staging), materialized shard-by-shard
  into a *preallocated* host slab at collect time (no per-batch
  allocation; the copy of shard *i* overlaps the in-flight transfer of
  shard *i+1*);
- :class:`AsyncCodecPlane` — a bounded-window, order-preserving encoder
  over the existing ``JpegCodec``/``NativeJpegCodec`` thread pools
  (``encode_batch_async`` futures): the delivery loop submits a batch's
  rows and returns to decoding/computing the NEXT batch while the pool
  encodes; completed batches drain in submission order.

Timeline, monolithic vs streamed (worker-style decode→compute→encode):

    monolithic   decode ████ compute ████ fetch ███ encode ██████ send █
    streamed     decode ████ compute ████ fetch ▒█          (prefetch hid
                 encode        ░░ batch k−1 ░░  ██████       most of it)
                 send                            batch k−1 █

Fallbacks mirror the ingest assembler, recorded in the stats either way:

- ``mode="monolithic"`` (the ``--egress monolithic`` escape hatch) and
  results that are not shard-addressable keep the classic
  ``np.asarray`` fetch — byte-for-byte the pre-streaming behavior;
- a CPU-backend result's ``np.asarray`` is already a zero-copy view of
  the runtime buffer, so any slab copy is pure added work
  (``fallback_reason="zero_copy_backend"``; tests monkeypatch
  ``STREAM_ON_CPU`` to exercise the machinery);
- a calibrated blocking fetch (``Engine.d2h_block_ms``) below the fixed
  streaming overhead stays monolithic (``"cheap_transfer"``, the mirror
  of ingest's ``MIN_STREAM_H2D_MS`` guard);
- repeated d2h faults degrade streamed → monolithic through the error
  budget (``"d2h_fault_budget"``, wired in pipeline/serve/worker).

Slot discipline is the staging-pool contract unchanged: the caller
provides a monotonically increasing slot id per batch and guarantees
(via its in-flight bound / encode window) that a slab is only revisited
after its rows have been copied onward or sent.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from dvf_tpu.obs.metrics import EgressStats
from dvf_tpu.obs.trace import EGRESS_D2H, EGRESS_ENCODE

EGRESS_MODES = ("streamed", "monolithic")

# Below this calibrated blocking-fetch cost (Engine.d2h_block_ms,
# measured at compile), the fixed per-batch streaming overhead — shard
# iteration, slab scatter — exceeds anything overlap can hide, so the
# fetcher stays monolithic. Mirror of ingest.MIN_STREAM_H2D_MS; tests
# that exercise the streaming machinery at tiny sizes monkeypatch to 0.
MIN_STREAM_D2H_MS = 2.0

# On the CPU backend ``np.asarray(result)`` of a single-device result is
# a zero-copy view — the monolithic path costs literally nothing, and a
# slab copy would be a pure regression. Tests monkeypatch True to run
# the streamed machinery on the CPU test backend.
STREAM_ON_CPU = False

# Host-slab accounting registry (obs.memory) — the egress mirror of
# runtime.ingest._LIVE_ASSEMBLERS: scrape-time gauges and the conftest
# session-end leak guard walk it; a released fetcher reports 0.
_LIVE_FETCHERS: "weakref.WeakSet" = weakref.WeakSet()


def live_fetchers() -> List["ShardedBatchFetcher"]:
    return list(_LIVE_FETCHERS)


def occupied_slab_bytes() -> int:
    """Total host delivery-slab bytes currently pinned by live fetchers
    — the egress half of ``dvf_mem_host_slab_bytes``."""
    return sum(f.slab_bytes() for f in live_fetchers())


class ShardedBatchFetcher:
    """Fetches engine results into preallocated host slabs, per shard.

    One fetcher per (output signature, sharding); ``prefetch(result)``
    belongs right after ``Engine.submit`` (it issues the per-shard
    ``copy_to_host_async`` so the transfer overlaps the tail of compute),
    ``fetch(result, slot)`` belongs in the collect path (it materializes
    into the slot's slab and only *waits*, never initiates).

    The returned array is the slab itself on the streamed path — valid
    until the slot is revisited (the caller's in-flight bound), so
    consumers that hold rows longer (reorder buffers) must copy them.
    ``effective_mode`` tells the caller which contract applies; the
    monolithic path returns a fresh per-batch array exactly as before.
    """

    def __init__(
        self,
        out_shape: Tuple[int, ...],
        dtype,
        sharding=None,
        mode: str = "streamed",
        slots: int = 5,
        stats: Optional[EgressStats] = None,
        tracer=None,
        track: int = 0,
        chaos=None,
    ):
        if mode not in EGRESS_MODES:
            raise ValueError(f"egress mode must be one of {EGRESS_MODES}, "
                             f"got {mode!r}")
        self.out_shape = tuple(out_shape)
        self.dtype = np.dtype(dtype)
        self.sharding = sharding
        self.mode = mode
        self.slots = max(1, slots)
        self.tracer = tracer
        self.track = track
        self.chaos = chaos  # resilience.chaos.FaultPlan — the "d2h"
        #   injection site fires per shard fetch when armed
        self.stats = stats if stats is not None else EgressStats(
            requested_mode=mode)
        self._pool: Optional[List[np.ndarray]] = None
        self.effective_mode = self._plan()
        self.stats.effective_mode = self.effective_mode
        _LIVE_FETCHERS.add(self)

    def slab_bytes(self) -> int:
        """Host delivery-slab memory this fetcher currently pins — 0
        after :meth:`release` (and always 0 on the monolithic path,
        which allocates per batch instead of pooling)."""
        if self._pool is None:
            return 0
        return sum(a.nbytes for a in self._pool)

    def _plan(self) -> str:
        if self.mode == "monolithic" or self.sharding is None:
            return "monolithic"
        try:
            dev = next(iter(self.sharding.device_set))
            if dev.platform == "cpu" and not STREAM_ON_CPU:
                self.stats.fallback_reason = "zero_copy_backend"
                return "monolithic"
        except Exception:  # noqa: BLE001 — exotic sharding: stay correct
            self.stats.fallback_reason = "unsupported_sharding"
            return "monolithic"
        cal = self.stats.d2h_block_ms
        if cal is not None and cal < MIN_STREAM_D2H_MS:
            self.stats.fallback_reason = "cheap_transfer"
            return "monolithic"
        self._pool = [np.empty(self.out_shape, self.dtype)
                      for _ in range(self.slots)]
        self.stats.pool_allocs += 1
        return "streamed"

    # -- submit side ----------------------------------------------------

    def prefetch(self, result: Any) -> None:
        """Start the D2H now, overlapped with the next batch's staging and
        the tail of this batch's compute; ``fetch`` then only waits for
        completion instead of initiating the copy. Per shard on the
        streamed path so each shard's copy is independently in flight."""
        try:
            if self.effective_mode == "streamed":
                seen = set()
                for sh in result.addressable_shards:
                    # Same dedupe as fetch(): replicated placements hold
                    # identical bytes on every device — starting N
                    # identical transfers would waste N−1 batches of
                    # link bandwidth on the submit hot path.
                    key = tuple((sl.start, sl.stop, sl.step)
                                for sl in sh.index)
                    if key in seen:
                        continue
                    seen.add(key)
                    sh.data.copy_to_host_async()
            else:
                result.copy_to_host_async()
        except AttributeError:
            pass  # non-jax results (tests/fakes) have nothing to prefetch

    # -- collect side ---------------------------------------------------

    def _streamable(self, result: Any) -> bool:
        return (self.effective_mode == "streamed"
                and self._pool is not None  # released mid-flight (egress
                #   degradation, hot swap): a plan-pinned fetcher must
                #   fall back per batch, not scatter into freed slabs
                and hasattr(result, "addressable_shards")
                and getattr(result, "is_fully_addressable", True)
                and tuple(result.shape) == self.out_shape)

    def fetch(self, result: Any, slot: int) -> np.ndarray:
        """Materialize one batch; blocks until the device is done (like
        the ``np.asarray`` it replaces) but scatters shard host copies
        into the slot's preallocated slab as each one lands."""
        t_begin = time.perf_counter()
        if not self._streamable(result):
            # A mid-stream geometry change can hand this fetcher a batch
            # compiled at another signature — fall back per batch rather
            # than corrupt the slab. (Intentional monolithic mode and
            # non-jax results land here too: the classic fetch.)
            out = np.asarray(result)
            self.stats.record_fetch(
                wait_ms=0.0, copy_ms=0.0,
                span_ms=(time.perf_counter() - t_begin) * 1e3)
            return out
        # Compute wait is not D2H: exclude it from the exposed-transfer
        # clock so overlap_efficiency judges the fetch, not the device.
        try:
            result.block_until_ready()
        except AttributeError:
            pass
        slab = self._pool[slot % self.slots]
        wait_s = 0.0
        copy_s = 0.0
        seen = set()
        tracer = self.tracer
        for sh in result.addressable_shards:
            # Replicated output placements hold identical bytes on every
            # device — one host copy per distinct index range is enough.
            key = tuple((sl.start, sl.stop, sl.step) for sl in sh.index)
            if key in seen:
                continue
            seen.add(key)
            if self.chaos is not None:
                # Injection site "d2h": a delay rule stalls this shard's
                # fetch (models a congested link), a raise rule denies it
                # — exactly where a real transfer fault would surface.
                self.chaos.fire("d2h")
            t0 = time.perf_counter()
            host = np.asarray(sh.data)  # waits on THIS shard's copy only
            t1 = time.perf_counter()
            np.copyto(slab[sh.index], host)
            t2 = time.perf_counter()
            wait_s += t1 - t0
            copy_s += t2 - t1
            if tracer is not None and tracer.enabled:
                off = time.time() - time.perf_counter()  # monotonic → wall
                b0 = sh.index[0]
                tracer.complete(
                    EGRESS_D2H, t0 + off, t2 + off, self.track,
                    rows=f"{b0.start or 0}:{b0.stop}", bytes=host.nbytes)
        self.stats.record_fetch(
            wait_ms=wait_s * 1e3, copy_ms=copy_s * 1e3,
            span_ms=(time.perf_counter() - t_begin) * 1e3)
        return slab

    def owns(self, out: np.ndarray) -> bool:
        """True when ``out`` is one of this fetcher's pooled slabs — i.e.
        it will be rewritten once the slot cycles, so rows that outlive
        the caller's collect step must be copied. The monolithic and
        per-batch-fallback paths return fresh arrays and stay False."""
        return self._pool is not None and any(out is s for s in self._pool)

    def release(self) -> None:
        """Drop the slab pool eagerly (geometry re-probe / degradation:
        same rationale as ``ShardedBatchAssembler.release``)."""
        self._pool = None


class _EncodeEntry:
    __slots__ = ("metas", "futures", "payloads", "t_submit", "t_done",
                 "_remaining", "_lock")

    def __init__(self, metas, futures, payloads, t_submit):
        self.metas = metas
        self.futures = futures      # None on the raw (no-encode) path
        self.payloads = payloads    # raw path: zero-copy memoryviews
        self.t_submit = t_submit
        self.t_done = t_submit
        self._remaining = len(futures) if futures else 0
        self._lock = threading.Lock()

    def mark_done(self) -> None:
        """Done-callback (pool thread): stamps the batch's encode span
        end when its last future completes."""
        with self._lock:
            self._remaining -= 1
            if self._remaining == 0:
                self.t_done = time.perf_counter()

    def done(self) -> bool:
        if self.futures is None:
            return True
        return all(f.done() for f in self.futures)

    def collect(self) -> List[Tuple[Any, Any, Optional[BaseException]]]:
        """(meta, payload, error) per row, in submission order; a failed
        encode surfaces as its row's error instead of poisoning the
        batch."""
        if self.futures is None:
            return [(m, p, None) for m, p in zip(self.metas, self.payloads)]
        out = []
        for meta, fut in zip(self.metas, self.futures):
            try:
                out.append((meta, fut.result(), None))
            except Exception as e:  # noqa: BLE001 — per-row containment
                out.append((meta, None, e))
        return out


class AsyncCodecPlane:
    """Bounded-window, order-preserving async encode over a codec pool.

    ``submit(rows, metas)`` hands one batch's rows to the codec's thread
    pool (``encode_batch_async``) and returns immediately; ``ready()``
    drains *completed head* batches — delivery order is submission order,
    never completion order. ``ready(block=True)`` (or ``flush``) waits
    for the head, which is how callers enforce the in-flight window:

        plane.submit(rows, metas)
        for batch in plane.ready(block=len(plane) > plane.depth):
            for meta, payload, err in batch: …send…

    The raw (``jpeg=False``) path skips the pool entirely and carries
    each row as a zero-copy memoryview over the caller's slab — valid
    until the slab slot is reused, which the window bound guarantees
    happens only after the send (zmq copies at send time).

    Thread contract: ``submit``/``ready``/``flush`` are called from one
    delivery thread; only the future done-callbacks run in pool threads.
    """

    def __init__(self, codec, jpeg: bool = True, depth: int = 2,
                 stats: Optional[EgressStats] = None, tracer=None,
                 track: int = 0):
        if depth < 1:
            raise ValueError("encode depth must be >= 1")
        self.codec = codec
        self.jpeg = jpeg
        self.depth = depth
        self.stats = stats
        self.tracer = tracer
        self.track = track
        self._pending: "deque[_EncodeEntry]" = deque()

    def __len__(self) -> int:
        return len(self._pending)

    def submit(self, rows: Sequence[np.ndarray], metas: Sequence[Any],
               bitmaps: Optional[Sequence[np.ndarray]] = None,
               coeffs: Optional[Sequence[Any]] = None) -> None:
        """``bitmaps`` (delta wire only): per-row device-computed dirty-
        tile reductions (runtime.codec_assist.DeviceDeltaProbe), handed
        through to ``DeltaCodec.encode_batch_async`` so the host skips
        its own change-detection pass. Ignored by full-frame codecs.

        ``coeffs`` (full-transform assist): per-row
        ``transport.codec.CoefficientFrame`` handles from the fused
        device pass — the codec entropy-codes device-quantized blocks
        and never touches pixels, so ``rows`` may be ``[None, ...]``."""
        t0 = time.perf_counter()
        if self.jpeg:
            if coeffs is not None:
                futures = self.codec.encode_batch_async(
                    rows, bitmaps=bitmaps, coeffs=coeffs)
            elif bitmaps is not None:
                futures = self.codec.encode_batch_async(rows,
                                                        bitmaps=bitmaps)
            else:
                futures = self.codec.encode_batch_async(rows)
            entry = _EncodeEntry(list(metas), futures, None, t0)
            for f in futures:
                f.add_done_callback(lambda _f, e=entry: e.mark_done())
        else:
            # Raw wire: zero-copy memoryviews over the staged slab rows
            # (flattened — the wire carries bytes, not shapes).
            payloads = [row.reshape(-1).data for row in rows]
            entry = _EncodeEntry(list(metas), None, payloads, t0)
        self._pending.append(entry)

    def ready(self, block: bool = False) -> List[list]:
        """Completed head batches, each a list of (meta, payload, error)
        rows. ``block=True`` waits for at least the head batch (the
        window-bound path); completed non-head batches always wait their
        turn — ordered delivery is the contract."""
        out = []
        while self._pending:
            entry = self._pending[0]
            if not entry.done():
                if not block:
                    break
                tw = time.perf_counter()
                if entry.futures is not None:
                    for f in entry.futures:
                        try:
                            f.exception()  # waits; result errors surface
                        except Exception:  # noqa: BLE001 — in collect()
                            pass
                wait_ms = (time.perf_counter() - tw) * 1e3
            else:
                wait_ms = 0.0
            self._pending.popleft()
            block = False  # only the head is owed a wait
            # Future.done() flips before done-callbacks run, so the batch
            # can be observed complete with t_done not yet stamped by
            # mark_done — stamp it here rather than record a 0 ms span.
            t_done = entry.t_done
            if entry.futures is not None and t_done <= entry.t_submit:
                entry.t_done = t_done = time.perf_counter()
            if self.stats is not None:
                self.stats.record_encode(
                    encode_ms=(t_done - entry.t_submit) * 1e3,
                    wait_ms=wait_ms)
                # Full-transform assist: drain the host entropy-coding
                # time the codec accumulated for this batch — on that
                # path it is the entire host codec cost (encode_ms wall
                # span still includes pool queueing / drain overlap).
                take = getattr(self.codec, "take_entropy_ms", None)
                if take is not None:
                    ms = take()
                    if ms > 0.0:
                        self.stats.record_entropy(ms)
            tracer = self.tracer
            if tracer is not None and tracer.enabled and entry.futures:
                off = time.time() - time.perf_counter()
                tracer.complete(EGRESS_ENCODE, entry.t_submit + off,
                                max(entry.t_done, entry.t_submit) + off,
                                self.track, rows=len(entry.metas))
            out.append(entry.collect())
        return out

    def flush(self) -> List[list]:
        """Drain everything, blocking until the pool finishes."""
        out = []
        while self._pending:
            out.extend(self.ready(block=True))
        return out
