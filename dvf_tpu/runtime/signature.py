"""Canonical serving signatures: the multi-tenant bucketing key.

A serving signature is the triple ``(op_chain, geometry, dtype)`` — what
one compiled device program can serve. Everything that keys on a
signature (the frontend's bucket map, the compiled-program pool, the
persistent compilation cache, the fleet's warm-replica preference) MUST
agree on spelling, or equal programs miss each other: ``uint8`` vs
``u8``, ``(16, 24, 3)`` vs ``[16, 24, 3]``, ``gaussian_blur(sigma=2,
ksize=9)`` vs ``gaussian_blur(ksize=9, sigma=2.0)`` are all the same
program, and a pool/cache keyed on raw client spellings would recompile
each of them. This module states the canonical form ONCE:

- **dtype**: numpy's canonical name via ``np.dtype``, with the ML
  shorthand aliases (``u8``→uint8, ``f32``→float32, ``bf16``→bfloat16 …)
  resolved FIRST — numpy itself reads ``'u8'`` as an 8-BYTE unsigned
  (uint64), which is never what a video client means.
- **geometry**: a tuple of python ints, whatever sequence type (list,
  tuple, np.shape) the client passed.
- **op_chain**: a ``|``-separated chain of registry filter specs, each
  ``name`` or ``name(k=v, ...)``, re-rendered with sorted kwargs and
  normalized numeric literals (``2`` ≡ ``2.0`` only when the value IS
  integral — filter factories receive the parsed python value, so the
  canonical string and the built filter can't diverge).

``build_filter`` turns the canonical chain into a live
:class:`~dvf_tpu.api.filter.Filter` through the ops registry — the
factory the frontend's bucket admission and the ``--precompile``
manifest both compile through.
"""

from __future__ import annotations

import ast
import re
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np

# ML-shorthand dtype spellings (bits, not numpy's byte-width codes:
# numpy parses "u8" as uint64). Resolved before np.dtype sees the string.
DTYPE_ALIASES = {
    "u8": "uint8", "u16": "uint16", "u32": "uint32",
    "i8": "int8", "i16": "int16", "i32": "int32",
    "f16": "float16", "f32": "float32", "f64": "float64",
    "bf16": "bfloat16", "half": "float16", "float": "float32",
    "byte": "uint8",
}

_STEP_RE = re.compile(r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*(?:\((.*)\))?\s*$",
                      re.DOTALL)


def canonical_dtype(dtype: Any) -> np.dtype:
    """One np.dtype per spelling family (``u8`` ≡ ``uint8`` ≡
    ``np.uint8``). bfloat16 (no numpy scalar on some stacks) stays a
    string-named dtype when ml_dtypes is absent."""
    if dtype is None:
        return np.dtype(np.uint8)
    if isinstance(dtype, str):
        dtype = DTYPE_ALIASES.get(dtype.strip().lower(), dtype.strip().lower())
        if dtype == "bfloat16":
            try:
                import ml_dtypes

                return np.dtype(ml_dtypes.bfloat16)
            except ImportError:
                pass  # np.dtype("bfloat16") raises below on old numpy —
                #   callers on such stacks can't run bf16 anyway
    return np.dtype(dtype)


def canonical_geometry(geometry: Sequence[int]) -> Tuple[int, ...]:
    """Any int sequence → a plain int tuple (list ≡ tuple ≡ np shape)."""
    out = tuple(int(d) for d in geometry)
    if any(d <= 0 for d in out):
        raise ValueError(f"geometry must be positive, got {out}")
    return out


def _render_value(v: Any) -> str:
    """Canonical literal for one filter kwarg value."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        # 2.0 and 2 are the same factory argument numerically, but only
        # when integral — render integral floats as ints so the spelling
        # can't fork the key.
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, str):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_render_value(x) for x in v) + "]"
    return repr(v)


def _parse_value(text: str) -> Any:
    """One kwarg literal: python literals first, bare words as strings
    (``impl=jnp`` reads naturally in a CLI spec)."""
    text = text.strip()
    try:
        return ast.literal_eval(text)
    except (ValueError, SyntaxError):
        lowered = text.lower()
        if lowered in ("true", "false"):
            return lowered == "true"
        return text


def _split_args(body: str) -> List[str]:
    """Split a kwargs body on top-level commas (bracket-aware, so
    list-valued kwargs survive)."""
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return [p for p in (x.strip() for x in parts) if p]


def parse_op_chain(spec: str) -> List[Tuple[str, dict]]:
    """``"a|b(k=v)"`` → ``[("a", {}), ("b", {"k": v})]``.

    Raises ValueError on malformed steps — admission surfaces that as a
    refusal, not a geometry fault three layers later.
    """
    steps: List[Tuple[str, dict]] = []
    for raw in str(spec).split("|"):
        m = _STEP_RE.match(raw)
        if m is None or not raw.strip():
            raise ValueError(f"malformed op-chain step {raw!r} in {spec!r}")
        name, body = m.group(1), m.group(2)
        kwargs: dict = {}
        if body is not None and body.strip():
            for part in _split_args(body):
                if "=" not in part:
                    raise ValueError(
                        f"op-chain step {raw!r}: positional args are not "
                        f"canonical; use k=v")
                k, v = part.split("=", 1)
                kwargs[k.strip()] = _parse_value(v)
        steps.append((name.strip(), kwargs))
    return steps


def canonical_op_chain(spec: Union[str, Sequence]) -> str:
    """Canonical rendering of an op-chain spec.

    Accepts the spec string or an already-parsed ``[(name, kwargs)]``
    list. Whitespace, kwarg order, and numeric spellings normalize away;
    two specs that build the same filters render identically.
    """
    steps = parse_op_chain(spec) if isinstance(spec, str) else [
        (name, dict(kwargs or {})) for name, kwargs in spec]
    rendered = []
    for name, kwargs in steps:
        if kwargs:
            body = ",".join(f"{k}={_render_value(kwargs[k])}"
                            for k in sorted(kwargs))
            rendered.append(f"{name}({body})")
        else:
            rendered.append(name)
    return "|".join(rendered)


class SignatureKey(NamedTuple):
    """The canonical ``(op_chain, geometry, dtype)`` serving signature.

    ``dtype`` is stored as its canonical NAME (string) so keys hash,
    compare, pickle, and render identically across processes — a
    np.dtype member would compare fine but pickle as a richer object
    than the fleet's wire needs.
    """

    op_chain: str
    geometry: Tuple[int, ...]
    dtype: str

    def render(self) -> str:
        """Human/label form: ``invert|16x24x3|uint8`` (also the stats
        bucket key and the ``bucket=`` metric label value)."""
        dims = "x".join(str(d) for d in self.geometry)
        return f"{self.op_chain}|{dims}|{self.dtype}"

    @property
    def np_dtype(self) -> np.dtype:
        return canonical_dtype(self.dtype)


def make_key(op_chain: Union[str, Sequence], geometry: Sequence[int],
             dtype: Any = None) -> SignatureKey:
    """THE canonicalization entry point: every spelling of one signature
    maps to one key (unit-pinned by tests/test_multitenant.py)."""
    return SignatureKey(
        op_chain=canonical_op_chain(op_chain),
        geometry=canonical_geometry(geometry),
        dtype=canonical_dtype(dtype).name,
    )


def build_filter(op_chain: Union[str, Sequence]):
    """Canonical chain spec → one live Filter through the ops registry
    (FilterChain when the spec has >1 step — still ONE fused device
    program, exactly like the single-filter path)."""
    from dvf_tpu.api.filter import FilterChain
    from dvf_tpu.ops import get_filter

    steps = parse_op_chain(op_chain) if isinstance(op_chain, str) else [
        (name, dict(kwargs or {})) for name, kwargs in op_chain]
    members = [get_filter(name, **kwargs) for name, kwargs in steps]
    if len(members) == 1:
        return members[0]
    return FilterChain(*members)


def parse_manifest(doc: Any) -> List[dict]:
    """``--precompile`` manifest → normalized entry list.

    Accepted shapes (documented in docs/GUIDE.md "Serving a mixed
    workload"): a JSON list of entries, or ``{"signatures": [...]}``.
    Each entry: ``{"op_chain": str, "frame_shape": [H, W, C],
    "dtype": str (optional, default uint8)}``. Returns entries with a
    canonical ``key`` (SignatureKey) attached.
    """
    if isinstance(doc, dict):
        doc = doc.get("signatures", [])
    if not isinstance(doc, (list, tuple)):
        raise ValueError(
            "precompile manifest must be a list of signature entries or "
            "{'signatures': [...]}")
    out: List[dict] = []
    for i, entry in enumerate(doc):
        if not isinstance(entry, dict) or "op_chain" not in entry \
                or "frame_shape" not in entry:
            raise ValueError(
                f"manifest entry {i} needs 'op_chain' and 'frame_shape', "
                f"got {entry!r}")
        key = make_key(entry["op_chain"], entry["frame_shape"],
                       entry.get("dtype"))
        out.append({"op_chain": key.op_chain,
                    "frame_shape": key.geometry,
                    "dtype": key.dtype,
                    "key": key})
    return out


def canonical_op_chain_or_verbatim(name: Any) -> str:
    """Best-effort canonicalization for op-chain spellings that may not
    be registry specs: a parseable chain canonicalizes, an ad-hoc
    filter display name (e.g. a CONFIGURED filter resolved to its
    measured impl) is kept verbatim — still a stable, equal-compares
    key. Every surface that keys on a chain spelling it did not build
    itself (the frontend's default bucket, the fleet's warm map, the
    engine's pool key) MUST share this one fallback rule, or their keys
    diverge and equal programs miss the pool/cache by spelling."""
    try:
        return canonical_op_chain(name)
    except ValueError:
        return str(name)


def engine_signature_key(engine) -> Optional[SignatureKey]:
    """The canonical signature of a compiled Engine: its filter's
    op-chain spelling (best-effort canonicalized — a registry-built name
    like ``gaussian_blur(ksize=9)`` parses; an ad-hoc name is kept
    verbatim), per-frame geometry, and dtype. None before compile."""
    sig = engine.signature
    if sig is None:
        return None
    (batch_shape, dtype) = sig
    chain = canonical_op_chain_or_verbatim(engine.op_chain)
    return SignatureKey(chain, canonical_geometry(batch_shape[1:]),
                        canonical_dtype(dtype).name)
