"""The end-to-end pipeline: source → batch assembler → device → ordered sink.

Process-topology translation of SURVEY.md §3: the reference's 4 app threads
+ N worker processes collapse into one process with 3 threads around an
async device queue:

  ingest    — the capture thread (webcam_app.py:67-116): pulls frames from
              the source, indexes them (distributor.py:179-180), enqueues
              with drop-oldest backpressure (distributor.py:188-203);
  dispatch  — replaces the distribute thread + worker pool
              (distributor.py:205-251 / worker.py:30-76): drains the queue
              into a fixed-size batch (the batch generalizes the
              latest-frame slot, distributor.py:214-217), pads it, submits
              to the Engine; in-flight depth is bounded to cap latency;
  collect   — replaces the collect thread (distributor.py:253-289): waits
              for device results in submission order, feeds the reorder
              buffer, advances the display cursor, emits to the sink.

Ordering inside a batch is free (arrays are ordered); across batches it is
submission order on one mesh — the reorder buffer only really works when
results arrive from elastic out-of-order executors (ZMQ ingress mode), but
it is kept in-path so drop/delay semantics match the reference everywhere.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Optional

import numpy as np

from dvf_tpu.api.filter import Filter
from dvf_tpu.obs.export import attach_signal_provider
from dvf_tpu.obs.metrics import EgressStats, IngestStats, LatencyStats, RateLogger
from dvf_tpu.obs.registry import MetricsRegistry
from dvf_tpu.obs.trace import Tracer
from dvf_tpu.resilience.budget import ErrorBudget, escalate
from dvf_tpu.resilience.faults import FaultError, FaultKind, FaultStats, classify
from dvf_tpu.resilience.supervisor import Supervisor
from dvf_tpu.runtime.egress import EGRESS_MODES, ShardedBatchFetcher
from dvf_tpu.runtime.engine import Engine
from dvf_tpu.runtime.ingest import INGEST_MODES, ShardedBatchAssembler
from dvf_tpu.sched.queues import DropOldestQueue
from dvf_tpu.sched.reorder import ReorderBuffer

# Trace track ids (the reference maps worker pids to tracks,
# distributor.py:129; our executors are stages, not processes).
# TRACK_H2D is the streamed-ingest transfer lane (per-shard h2d spans);
# TRACK_D2H is the streamed-egress mirror (per-shard egress_d2h spans).
TRACK_INGEST, TRACK_DEVICE, TRACK_SINK, TRACK_H2D, TRACK_D2H = 0, 1, 2, 3, 4


@dataclasses.dataclass
class PipelineConfig:
    batch_size: int = 8
    frame_delay: int = 5          # display-cursor lag, reference default (webcam_app.py:17)
    queue_size: int = 10          # ingest queue bound (distributor.py:11)
    reorder_capacity: int = 50    # reorder cap (distributor.py:23)
    max_inflight: int = 4         # batches in flight; bounds latency
    assemble_timeout_s: float = 0.01   # like the 10ms polls (distributor.py:224)
    trace: bool = False           # enable_trace_export (distributor.py:9)
    resilient: bool = False       # per-iteration error containment: one bad
    #   frame/batch is dropped+counted, the loops keep running — the
    #   reference's live-mode semantics (distributor.py:249-251,287-289,
    #   worker.py:71-76). Off by default so tests/benches fail fast.
    telemetry_interval_s: float = 0.0  # >0: print capture/deliver fps every
    #   N s, like the reference's 5 s prints (webcam_app.py:88-95,152-163)
    collect_mode: str = "thread"  # "thread": dedicated collect thread
    #   (default); "inline": the dispatch thread collects the oldest
    #   in-flight batch itself once the window fills — one consumer thread
    #   total, less GIL contention (XLA still overlaps compute with host
    #   staging via async dispatch). Ordering is identical: batches retire
    #   oldest-first either way.
    ingest: str = "streamed"      # batch staging → device transfer path:
    #   "streamed" (default) decodes frames into per-device-shard slabs
    #   and device_puts each shard the moment its rows fill, overlapping
    #   H2D with decode and with the previous batch's compute
    #   (runtime/ingest.py); "monolithic" is the escape hatch — the
    #   pre-streaming decode-all → stage-all → one blocking put path.
    ingest_depth: int = 4         # dispatch-depth knob: how many shard
    #   transfers may be in flight before the assembler blocks on the
    #   oldest (also the sub-chunking granularity of a device's shard)
    egress: str = "streamed"      # result fetch path: "streamed" (default)
    #   issues per-output-shard copy_to_host_async at submit and
    #   materializes shard-by-shard into a preallocated host slab at
    #   collect (runtime/egress.py — auto-degrades where streaming cannot
    #   win, e.g. the CPU backend's zero-copy np.asarray); "monolithic"
    #   is the escape hatch — the classic whole-batch np.asarray fetch.
    fault_budget: int = 16        # contained faults per kind inside
    #   fault_window_s before containment escalates (resilience.budget:
    #   drop → degrade → fail); resilient mode only
    fault_window_s: float = 30.0
    stall_timeout_s: float = 0.0  # >0: arm the stall watchdog
    #   (resilience.supervisor) — an in-flight batch older than this trips
    #   recovery (resilient + thread collect: shed the window, rebuild the
    #   engine; otherwise: abort with a stall FaultError). 0 = off, the
    #   pre-supervision behavior.
    chaos: Any = None             # resilience.chaos.FaultPlan — arms the
    #   deterministic fault-injection sites in the engine, assembler, and
    #   collect loop (--chaos CLI spec); None = zero overhead
    device_trace_dir: Optional[str] = None  # capture a jax.profiler device
    #   trace for the whole run into this dir — Perfetto-compatible, views
    #   alongside the host-side frame-lifecycle trace (obs.trace) in one
    #   UI; with trace=True the merged host+device export
    #   (dvf_merged_timing.pftrace) also lands in this dir
    flight_dir: Optional[str] = None  # flight recorder (obs.export): a
    #   watchdog trip or hard pipeline failure dumps the bounded
    #   post-mortem (trace window + stats) here — the single-stream
    #   tier's spelling of serve/fleet --flight-dir. None = off.
    flight_min_interval_s: float = 10.0  # dump rate limit


class Pipeline:
    def __init__(
        self,
        source: Any,
        filt: Filter,
        sink: Any,
        config: Optional[PipelineConfig] = None,
        engine: Optional[Engine] = None,
        queue: Optional[Any] = None,
    ):
        if filt.stateful and not filt.pad_safe:
            # The dispatch loop pads short batches (end-of-stream tail, slow
            # sources) by repeating the last frame; a pad-unsafe stateful
            # filter would silently corrupt its temporal state (Filter.pad_safe).
            raise ValueError(
                f"filter {filt.name!r} is stateful and not pad-safe; the "
                f"pipeline pads short batches and cannot run it"
            )
        self.source = source
        self.sink = sink
        self.config = config or PipelineConfig()
        if self.config.collect_mode not in ("thread", "inline"):
            raise ValueError(
                f"collect_mode must be 'thread' or 'inline', got "
                f"{self.config.collect_mode!r}")
        if self.config.ingest not in INGEST_MODES:
            raise ValueError(
                f"ingest must be one of {INGEST_MODES}, got "
                f"{self.config.ingest!r}")
        if self.config.egress not in EGRESS_MODES:
            raise ValueError(
                f"egress must be one of {EGRESS_MODES}, got "
                f"{self.config.egress!r}")
        self.engine = engine or Engine(filt, chaos=self.config.chaos)
        if self.config.chaos is not None and self.engine.chaos is None:
            self.engine.chaos = self.config.chaos  # arm a caller-built engine
        self.tracer = Tracer(enabled=self.config.trace)
        # Injectable ingest queue: default is the Python drop-oldest queue;
        # `--transport ring` passes a transport.ring_queue.RingFrameQueue,
        # putting the native C++ ring on the hot path (frames then cross
        # ingest→assembler as serialized payloads, decoded straight into
        # the dispatch staging buffer via queue.decode_into).
        self.queue = queue if queue is not None else DropOldestQueue(
            maxsize=self.config.queue_size)
        self.reorder = ReorderBuffer(
            frame_delay=self.config.frame_delay,
            capacity=self.config.reorder_capacity,
        )
        self.latency = LatencyStats()
        self.frame_counter = 0
        self.errors = 0
        self.faults = FaultStats()      # per-kind counters + last errors
        self.recoveries = 0             # supervisor engine rebuilds
        self._budget = ErrorBudget(limit=self.config.fault_budget,
                                   window_s=self.config.fault_window_s)
        # Stall escalation is consecutive, not time-windowed: stalls
        # arrive at most once per stall_timeout_s, so a sliding window
        # could never fill. Recoveries with no delivered batch in between
        # (delivery resets the counter) fail hard — the pipeline cannot
        # replace a permanently wedged collect thread, so it must not
        # shed-rebuild at 0 fps forever.
        self._stalls_since_progress = 0
        self._stall_fail_after = max(2, self.config.fault_budget // 4)
        self._ingest_mode = self.config.ingest  # may degrade to monolithic
        #   after repeated h2d faults (budget escalation)
        self._degrade_reason: Optional[str] = None
        self._egress_mode = self.config.egress  # the d2h mirror of the
        #   above: repeated d2h faults degrade streamed → monolithic fetch
        self._egress_degrade_reason: Optional[str] = None
        self._fetcher: Optional[ShardedBatchFetcher] = None
        self._egress_stats: Optional[EgressStats] = None
        self._supervisor: Optional[Supervisor] = None
        self._recovering = threading.Event()  # dispatch parks while the
        #   supervisor swaps the engine/assembler (see _on_stall)
        # Metrics registry (obs.registry): the scrape endpoint's source
        # for this pipeline. The RateLoggers land their computed rates as
        # the rate_fps gauge ON THE SAME TICKS they print, so the every-5s
        # stderr numbers and /metrics can never disagree; the provider
        # adapts signals() (delivered/dropped/faults/overlap) at scrape.
        self.registry = MetricsRegistry()
        attach_signal_provider(self.registry, "pipeline", self.signals)
        self.flight = None
        if self.config.flight_dir:
            from dvf_tpu.obs.export import FlightRecorder

            self.flight = FlightRecorder(
                self.config.flight_dir, label="pipeline",
                min_interval_s=self.config.flight_min_interval_s,
                trace_fn=lambda: [self.tracer.snapshot()],
                stats_fn=self.stats)
        _ti = self.config.telemetry_interval_s
        self._capture_rate = RateLogger("capture", _ti if _ti > 0 else 5.0,
                                        quiet=_ti <= 0,
                                        registry=self.registry)
        self._deliver_rate = RateLogger("deliver", _ti if _ti > 0 else 5.0,
                                        quiet=_ti <= 0,
                                        registry=self.registry)
        self._assembler: Optional[ShardedBatchAssembler] = None
        self._ingest_stats: Optional[IngestStats] = None
        self._on_idle = None  # inline collect: drain-ready hook (_assemble)
        self._inflight: "DropOldestQueue" = DropOldestQueue(maxsize=1_000_000)
        self._inflight_sem = threading.Semaphore(self.config.max_inflight)
        self._eof = threading.Event()
        self._dispatch_done = threading.Event()
        self._abort = threading.Event()
        self._stop_requested = threading.Event()
        self._error: Optional[BaseException] = None

    def stop(self) -> None:
        """Graceful shutdown: stop ingesting, drain what's in flight,
        deliver the tail, then run() finishes normally (stats print, sink
        close, trace export) — the reference's cleanup() path
        (webcam_app.py:172-180 → distributor.py:356-376). Safe to call
        from signal handlers, the display's ESC callback, or any thread."""
        self._stop_requested.set()

    def abort(self) -> None:
        """Hard stop: drop everything in flight and unwind now (second
        Ctrl-C semantics)."""
        self._stop_requested.set()
        self._abort.set()

    # ------------------------------------------------------------------

    def _ingest(self) -> None:
        it = iter(self.source)
        try:
            while not self._abort.is_set() and not self._stop_requested.is_set():
                try:
                    frame, ts = next(it)
                except StopIteration:
                    break
                except Exception as e:  # noqa: BLE001 — bad read, maybe next works
                    if not self._contain(e, "ingest"):
                        return
                    continue
                if frame is None:
                    break
                idx = self.frame_counter
                self.frame_counter += 1
                evicted = self.queue.put((idx, frame, ts))
                if evicted is not None:
                    # The source is outrunning the pipeline (put evicted an
                    # older frame — drop-oldest semantics, so freshness is
                    # already preserved). Pace this thread: an unthrottled
                    # source spinning here starves dispatch/collect of the
                    # GIL and *triples* e2e frame time (measured on CPU:
                    # 44→135 fps at 1080p just from this yield). 200 µs
                    # caps the drop loop at ~5k puts/s, far above any
                    # full-frame delivery rate a host link can sustain.
                    time.sleep(0.0002)
                self._capture_rate.tick()
                self.tracer.instant("frame_captured", ts, TRACK_INGEST, frame=idx)
        except BaseException as e:  # noqa: BLE001
            self._fail(e)
        finally:
            self._eof.set()
            # Release the source promptly (camera handle — the reference
            # does cap.release() in cleanup(), webcam_app.py:174-177).
            # Generator sources run their finally on .close().
            if hasattr(it, "close"):
                try:
                    it.close()
                except Exception:
                    pass

    def _fail(self, e: BaseException) -> None:
        first = self._error is None
        if first:
            self._error = e
        self._abort.set()
        if first and self.flight is not None:
            # Hard failure: the post-mortem moment (serve's discipline —
            # off-thread, rate-limited in the recorder).
            self.flight.trigger_async(f"pipeline failed: {e!r}")

    def _flight_trip(self, reason: str) -> None:
        """Supervisor on_trip tap: dump the black box before recovery
        tears the evidence down (off-thread — a disk write must not
        extend the stall it records)."""
        if self.flight is not None:
            self.flight.trigger_async(reason)

    def _contain(self, e: BaseException, where: str) -> bool:
        """Resilient mode: drop, count, continue (the reference's
        per-iteration ``except: continue``, distributor.py:249-251,287-289)
        — but classified (resilience.faults) and bounded by the per-kind
        error budget: the first overflow degrades (streamed→monolithic
        ingest for h2d faults), the second fails hard, so a permanently
        broken stage surfaces instead of shedding frames forever.
        Fail-fast mode: abort the pipeline. Returns True to continue."""
        kind = classify(e, site=where)
        self.faults.record(kind, e)
        if not (self.config.resilient and isinstance(e, Exception)):
            self._fail(e)
            return False
        self.errors += 1
        if escalate(self._budget, kind, self._degrade) == ErrorBudget.CONTAIN:
            # stderr: stdout is a data channel (one-JSON-line contract in
            # the bench stack and CLI).
            print(f"[pipeline:{where}] {kind} fault (continuing): {e!r}",
                  file=sys.stderr, flush=True)
            return True
        self._fail(FaultError(
            kind,
            f"error budget exhausted for {kind!r} faults "
            f"(> {self.config.fault_budget} in "
            f"{self.config.fault_window_s:g}s, no degradation left); "
            f"last: {e!r}"))
        return False

    def _degrade(self, kind: str) -> bool:
        """Apply this kind's degradation, if one exists. h2d: fall back
        from streamed to monolithic ingest (the same auto-degrade the
        assembler does for replicated layouts, here forced by fault
        pressure — reason recorded in the ingest stats). Returns True if
        a degradation was applied."""
        if kind == FaultKind.H2D and self._ingest_mode == "streamed":
            self._ingest_mode = "monolithic"
            self._degrade_reason = "h2d_fault_budget"
            self._assembler = None  # rebuilt monolithic on the next batch
            print("[pipeline] repeated h2d faults: degrading ingest "
                  "streamed → monolithic", file=sys.stderr, flush=True)
            return True
        if kind == FaultKind.D2H and self._egress_mode == "streamed":
            # The delivery-side mirror: repeated fetch faults fall back to
            # the whole-batch np.asarray path (reason recorded in stats).
            self._egress_mode = "monolithic"
            self._egress_degrade_reason = "d2h_fault_budget"
            old, self._fetcher = self._fetcher, None
            if old is not None:
                old.release()
            print("[pipeline] repeated d2h faults: degrading egress "
                  "streamed → monolithic", file=sys.stderr, flush=True)
            return True
        return False

    def _on_stall(self, reason: str) -> None:
        """Watchdog callback (supervisor thread): a submitted batch aged
        past stall_timeout_s. Resilient + thread-collect: shed the
        in-flight window (results written off, permits restored) and
        rebuild the engine — recompile, re-warm, re-calibrate — so a
        wedged device program can't freeze the stream forever. Inline
        collect (the dispatch thread is the one wedged) or fail-fast:
        abort with a stall fault."""
        e = FaultError(FaultKind.STALL, f"pipeline stalled: {reason}")
        self.faults.record(FaultKind.STALL, e)
        self._stalls_since_progress += 1
        recoverable = (self.config.resilient
                       and self.config.collect_mode == "thread"
                       and self._stalls_since_progress <= self._stall_fail_after)
        if not recoverable:
            self._fail(e)
            return
        self.errors += 1
        print(f"[pipeline] {reason}: shedding in-flight window and "
              f"rebuilding engine", file=sys.stderr, flush=True)
        # Park dispatch (it checks the flag between assembling and
        # staging): a batch submitted mid-recovery would route through
        # the old wedged engine and manufacture a follow-on stall. A
        # dispatch iteration already inside the staging/submit block
        # cannot be interrupted — its batch lands in the window and the
        # watchdog's next trip sheds it.
        self._recovering.set()
        try:
            shed = self._inflight.pop_up_to(len(self._inflight))
            for item in shed:
                self._supervisor.window.remove(item[0])
            # Rebuild BEFORE releasing the shed permits, so a dispatch
            # blocked on the semaphore wakes to the fresh engine.
            self.engine = self.engine.rebuild()
            self._assembler = None
            self._fetcher = None  # rebuilt against the fresh engine's
            #   re-calibrated d2h_block_ms on the next collect
            for _ in shed:
                self._inflight_sem.release()
            # A batch already popped by collect and still materializing
            # stays tracked only by that thread — its permit comes back
            # when np.asarray returns/raises there; clear its window
            # entry so the watchdog doesn't immediately re-trip on the
            # batch being shed.
            self._supervisor.window.drain()
            self.recoveries += 1
        finally:
            self._recovering.clear()

    def _assemble(self) -> Optional[list]:
        """Collect up to batch_size fresh frames; None = stream finished.

        FIFO consumption; drop-oldest freshness is enforced at the queue
        bound (put side), matching the reference (distributor.py:193-203).
        """
        b = self.config.batch_size
        items: list = self.queue.pop_up_to(b)
        deadline = None  # started at first frame, not at call time —
        # otherwise any source slower than the timeout per frame would
        # degenerate every batch to size 1.
        while len(items) < b and not self._abort.is_set():
            if items:
                if deadline is None:
                    deadline = time.perf_counter() + self.config.assemble_timeout_s
                elif time.perf_counter() > deadline:
                    break
            if self._eof.is_set() and len(self.queue) == 0:
                break
            got = self.queue.pop_up_to(b - len(items))
            if got:
                items.extend(got)
            else:
                if self._on_idle is not None:
                    # Inline collect mode: deliver any batch the device
                    # already finished while we wait for frames — a slow
                    # source must not hold completed results hostage to
                    # the in-flight window filling up.
                    self._on_idle()
                time.sleep(0.0005)
        if not items and (self._eof.is_set() or self._abort.is_set()):
            return None
        return items

    def _builder_for(self, frame_shape, dtype, slot: int):
        """One staged batch via the shared assembler (runtime/ingest.py).

        The assembler owns the preallocated staging pool — per-shard
        slabs (streamed) or whole-batch buffers (monolithic), one set per
        in-flight slot. Pool size is max_inflight + 1: the semaphore
        guarantees at most max_inflight batches outstanding, so the
        buffers being rewritten belong to a batch that has already been
        collected (the device consumed them long ago). Rebuilt only when
        the frame signature changes, exactly like the engine's compile.
        """
        shape = (self.config.batch_size, *frame_shape)
        dtype = np.dtype(dtype)
        asm = self._assembler
        if asm is None or asm.batch_shape != shape or asm.dtype != dtype:
            # The engine's compiled input sharding defines the shard
            # layout (and its warmup put calibrates the un-overlapped
            # H2D cost the overlap_efficiency metric is judged against).
            self.engine.ensure_compiled(shape, dtype)
            self._ingest_stats = IngestStats(
                requested_mode=self.config.ingest,
                depth=self.config.ingest_depth,
                h2d_block_ms=self.engine.h2d_block_ms)
            self._assembler = asm = ShardedBatchAssembler(
                shape, dtype, self.engine.input_sharding,
                mode=self._ingest_mode, depth=self.config.ingest_depth,
                slots=self.config.max_inflight + 1,
                tracer=self.tracer, track=TRACK_H2D,
                stats=self._ingest_stats, chaos=self.config.chaos)
            if self._degrade_reason is not None:
                # Budget-forced monolithic fallback: record why, like the
                # assembler's own replicated_layout/cheap_transfer reasons.
                self._ingest_stats.fallback_reason = self._degrade_reason
        return asm.begin(slot)

    def _fetcher_for(self):
        """The streamed-egress fetcher for the engine's compiled output
        signature (runtime/egress.py) — the delivery-side mirror of
        ``_builder_for``. Slab pool is max_inflight + 1, same slot
        discipline: the slab being rewritten belongs to a batch whose
        rows were already copied onward by collect. Rebuilt when the
        output signature changes (geometry change, engine rebuild)."""
        shape = getattr(self.engine, "out_shape", None)
        dtype = getattr(self.engine, "out_dtype", None)
        if shape is None:
            return None  # engine never compiled (shouldn't happen post-submit)
        f = self._fetcher
        if f is None or f.out_shape != tuple(shape) or f.dtype != dtype:
            self._egress_stats = EgressStats(
                requested_mode=self.config.egress,
                d2h_block_ms=self.engine.d2h_block_ms)
            self._fetcher = f = ShardedBatchFetcher(
                shape, dtype, self.engine.output_sharding,
                mode=self._egress_mode,
                slots=self.config.max_inflight + 1,
                stats=self._egress_stats,
                tracer=self.tracer, track=TRACK_D2H,
                chaos=self.config.chaos)
            if self._egress_degrade_reason is not None:
                self._egress_stats.fallback_reason = \
                    self._egress_degrade_reason
        return f

    def _drain_ready(self, pending: "deque") -> bool:
        """Inline collect: retire the oldest batch when the window is full,
        plus any already-completed results (oldest-first — retiring out of
        order would break the staging-reuse guarantee and serve no purpose,
        the reorder buffer waits on the oldest anyway). Returns False only
        when an error escaped containment."""
        while pending:
            if len(pending) < self.config.max_inflight:
                try:
                    ready = pending[0][3].is_ready()
                except AttributeError:  # non-jax result (tests/fakes)
                    break
                except Exception:  # noqa: BLE001 — poisoned async result:
                    # retire it NOW so _collect_one's np.asarray surfaces
                    # the error through the normal containment path (a
                    # raise from here would bypass resilient mode and kill
                    # the stream on one bad batch).
                    ready = True
                if not ready:
                    break
            if not self._collect_one(*pending.popleft(), release=False):
                return False
        return True

    def _dispatch(self) -> None:
        seq = 0
        inline = self.config.collect_mode == "inline"
        pending: "deque" = deque()  # inline mode's in-flight window
        if inline:
            self._on_idle = lambda: self._drain_ready(pending)
        try:
            while not self._abort.is_set():
                items = self._assemble()
                if items is None:
                    break
                if not items:
                    continue
                while self._recovering.is_set() and not self._abort.is_set():
                    # Stall recovery is swapping the engine/assembler:
                    # park with the assembled frames in hand — submitting
                    # now would route them through the old wedged engine
                    # mid-rebuild and manufacture a follow-on stall.
                    time.sleep(0.001)
                valid = len(items)
                if inline:
                    # Single-consumer mode: collect in-flight batches HERE
                    # — no collect thread, no semaphore, one thread fewer
                    # fighting for the GIL. Retire the oldest when the
                    # window is full (the deque bound keeps staging reuse
                    # safe: pool is max_inflight + 1) plus anything the
                    # device already finished.
                    if not self._drain_ready(pending):
                        return
                else:
                    # Bounded in-flight depth; poll so a dead collect
                    # thread (which stops releasing permits) can't wedge
                    # dispatch. Acquired BEFORE touching the staging
                    # buffer — the permit is what makes buffer reuse safe
                    # (see _staging_for).
                    while not self._inflight_sem.acquire(timeout=0.1):
                        if self._abort.is_set():
                            return
                try:
                    decode = getattr(self.queue, "decode_into", None)
                    if decode is not None:
                        # Ring transport: items carry serialized payloads;
                        # the queue decodes them (JPEG via the threaded
                        # codec) straight into the shard staging slabs,
                        # one window per shard chunk so the transfer of a
                        # decoded chunk overlaps the decode of the next.
                        builder = self._builder_for(
                            self.queue.frame_shape, self.queue.frame_dtype,
                            seq)
                        for start, stop in builder.windows(valid):
                            decode(items[start:stop],
                                   builder.window_view(start, stop))
                            builder.commit_window(start, stop)
                    else:
                        f0 = items[0][1]
                        builder = self._builder_for(f0.shape, f0.dtype, seq)
                        for row, (_, frame, _) in enumerate(items):
                            builder.write_row(row, frame)
                    # finish() pads short batches by repeating the last
                    # frame — static shapes mean one compilation; padded
                    # outputs are dropped (and repeat-last keeps temporal
                    # state correct, see Filter.pad_safe) — and flushes
                    # the remaining shard transfers.
                    batch, resident = builder.finish(valid)
                    t0 = time.time()
                    result = (self.engine.submit_resident(batch) if resident
                              else self.engine.submit(batch))
                    # Start the D2H now — per output shard on the streamed
                    # egress path — overlapped with the next batch's
                    # staging + device compute; the collect side's fetch
                    # then only waits for completion instead of initiating
                    # the copy (runtime/egress.py).
                    fetcher = self._fetcher_for()
                    if fetcher is not None:
                        fetcher.prefetch(result)
                except Exception as e:  # noqa: BLE001 — drop this batch
                    if not inline:
                        self._inflight_sem.release()
                    if not self._contain(e, "dispatch"):
                        return
                    continue
                if self._supervisor is not None:
                    # Watchdog window: this batch is now in flight; the
                    # collect side removes it once materialized (either
                    # way), so its age is the stall signal.
                    self._supervisor.window.add(seq)
                meta = [(idx, ts) for idx, _, ts in items]
                if inline:
                    pending.append((seq, meta, valid, result, t0))
                else:
                    self._inflight.put((seq, meta, valid, result, t0))
                seq += 1
            # Inline mode: drain the window (graceful stop / end of
            # stream). Hard abort drops it, matching the collect thread.
            while pending and not self._abort.is_set():
                if not self._collect_one(*pending.popleft(), release=False):
                    return
        except BaseException as e:  # noqa: BLE001
            self._fail(e)
        finally:
            self._dispatch_done.set()

    def _collect_one(self, seq, meta, valid, result, t0, release=True) -> bool:
        """Materialize one batch into the reorder buffer + sink; returns
        False only when an error escaped containment."""
        fetcher = self._fetcher
        try:
            # Streamed egress: shard-by-shard host copies into the slot's
            # preallocated slab (the D2H was issued at submit); monolithic
            # or a non-streamable result: the classic np.asarray, blocking
            # until the device is done.
            out = (fetcher.fetch(result, seq) if fetcher is not None
                   else np.asarray(result))
        except Exception as e:  # noqa: BLE001 — device error: drop batch
            if self._supervisor is not None:
                self._supervisor.window.remove(seq)
            if release:
                self._inflight_sem.release()
            return self._contain(e, "collect")
        if self._supervisor is not None:
            self._supervisor.window.remove(seq)
            self._stalls_since_progress = 0  # engine made real progress
        if release:
            self._inflight_sem.release()
        t1 = time.time()
        self.tracer.complete(
            "batch_complete", t0, t1, TRACK_DEVICE,
            frames=[i for i, _ in meta],
        )
        # Streamed fetch returns the slab itself, rewritten after
        # max_inflight + 1 batches — rows that outlive this call (the
        # reorder buffer holds them across the frame_delay window) must
        # own their bytes. The monolithic path's fresh per-batch array
        # keeps handing out views, exactly as before.
        copy_rows = fetcher is not None and fetcher.owns(out)
        for row, (idx, ts) in enumerate(meta[:valid]):
            frame = out[row].copy() if copy_rows else out[row]
            self.reorder.complete(idx, (frame, ts))
        self._deliver()
        return True

    def _collect(self) -> None:
        chaos = self.config.chaos
        try:
            while not self._abort.is_set():
                if chaos is not None:
                    chaos.fire("freeze")  # injection site: a delay rule
                    #   wedges this consumer so the stall watchdog has a
                    #   deterministic stall to catch
                try:
                    item = self._inflight.get(timeout=0.05)
                except TimeoutError:
                    if self._dispatch_done.is_set() and len(self._inflight) == 0:
                        break
                    continue
                if not self._collect_one(*item):
                    return
        except BaseException as e:  # noqa: BLE001
            self._fail(e)

    def _deliver(self, flush: bool = False) -> None:
        if flush:
            # End of stream: let the cursor catch up to the newest frame so
            # the tail (< frame_delay deep) still gets delivered.
            self.reorder.flush()
        self.reorder.advance()
        for idx, (frame, ts) in self.reorder.pop_ready():
            self.latency.record(time.time() - ts)
            self._deliver_rate.tick()
            self.tracer.instant("frame_delivered", track=TRACK_SINK, frame=idx)
            try:
                self.sink.emit(idx, frame, ts)
            except Exception as e:  # noqa: BLE001 — a display hiccup must not
                if not self._contain(e, "sink"):  # kill the stream
                    return

    # ------------------------------------------------------------------

    def run(self) -> dict:
        """Run to stream end (or Ctrl-C); returns a stats summary."""
        device_tracing = False
        if self.config.device_trace_dir:
            import jax

            jax.profiler.start_trace(self.config.device_trace_dir)
            # Host-clock epoch of the profiler session: what aligns the
            # device trace's relative timestamps with the host tracer's
            # in the merged export (obs.trace.merge_with_device_trace).
            self._device_trace_epoch = time.time()
            device_tracing = True
        threads = [
            threading.Thread(target=self._ingest, name="dvf-ingest", daemon=True),
            threading.Thread(target=self._dispatch, name="dvf-dispatch", daemon=True),
        ]
        if self.config.collect_mode != "inline":
            threads.append(
                threading.Thread(target=self._collect, name="dvf-collect", daemon=True))
        if self.config.stall_timeout_s > 0:
            self._supervisor = Supervisor(
                self.config.stall_timeout_s, on_stall=self._on_stall,
                name="dvf-pipeline-supervisor",
                on_trip=self._flight_trip).start()
        try:
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                try:
                    for t in threads:
                        t.join(timeout=0.2)
                except KeyboardInterrupt:
                    # First Ctrl-C: graceful stop — drain, deliver the
                    # tail, print stats, export the trace (the reference's
                    # signal → cleanup path, webcam_app.py:46-48,62-65).
                    # Second: abort.
                    if self._stop_requested.is_set():
                        self.abort()
                    else:
                        print("\n[pipeline] stopping (Ctrl-C again to abort)…",
                              file=sys.stderr, flush=True)
                        self.stop()
        finally:
            if self._supervisor is not None:
                self._supervisor.stop()
            # Always stop the profiler — the abort path (double Ctrl-C /
            # escaping exception) is exactly the run someone inspects.
            if device_tracing:
                import jax

                jax.profiler.stop_trace()
        if self._error is not None:
            raise self._error
        if not self._abort.is_set():
            # Drain the trailing frame_delay window — but not on hard
            # abort, whose contract is "unwind now", not "emit up to
            # reorder_capacity buffered frames through the sink first".
            self._deliver(flush=True)
        self.sink.close()
        if hasattr(self.queue, "close"):
            self.queue.close()  # ring transport: release shm + codec pool
        if self.tracer.enabled:
            host_trace = self.tracer.export()
            if host_trace and device_tracing:
                # §5.1's "merge in one UI", made literal: one file with
                # the host frame-lifecycle lanes above the device lanes,
                # clocks aligned via the recorded profiler epoch.
                from dvf_tpu.obs.trace import merge_with_device_trace

                try:
                    # Into device_trace_dir, beside the device trace it
                    # merges — a CWD-relative path would scatter the
                    # artifacts (or silently lose the merge in a
                    # read-only CWD).
                    merge_with_device_trace(
                        host_trace, self.config.device_trace_dir,
                        os.path.join(self.config.device_trace_dir,
                                     "dvf_merged_timing.pftrace"),
                        int((self._device_trace_epoch
                             - self.tracer.start_time) * 1e6))
                except Exception as e:  # noqa: BLE001 — teardown garnish:
                    # a merge failure (unwritable CWD, odd profiler
                    # output) must not fail a run that delivered.
                    print(f"[trace] merged export failed: {e!r}",
                          file=sys.stderr)
        return self.stats()

    def health(self) -> dict:
        """Cheap liveness export (the /healthz surface, mirroring
        ``ServeFrontend.health``): no percentile work, safe to poll at
        hertz rates. ``ok`` flips False once the pipeline has failed
        (fail-fast fault / escaped error)."""
        err = self._error
        return {
            "ok": err is None,
            "error": repr(err) if err is not None else None,
            "delivered": self.latency.count,
            "errors": self.errors,
            "recoveries": self.recoveries,
        }

    def signals(self) -> dict:
        """Flat load-control signal row (registry-conformant keys): the
        single-stream twin of ``ServeFrontend.signals`` — what the
        ``/metrics`` provider scrapes and a TimeSeriesRing samples."""
        agg = self.latency.summary()
        out = {
            "fps": agg.get("fps"),
            "p50_ms": agg.get("p50_ms"),
            "p90_ms": agg.get("p90_ms"),
            "p99_ms": agg.get("p99_ms"),
            "queue_depth": float(len(self.queue)),
            "inflight_batches": float(len(self._inflight)),
            "produced_total": float(self.frame_counter),
            "delivered_total": float(self.latency.count),
            "dropped_at_ingest_total": float(self.queue.dropped),
            "errors_total": float(self.errors),
            "recoveries_total": float(self.recoveries),
            "engine_batches_total": float(self.engine.stats.batches),
            "trace_dropped_total": float(self.tracer.dropped),
        }
        ing, egr = self._ingest_stats, self._egress_stats
        if ing is not None:
            out["ingest_overlap_efficiency"] = ing.overlap_efficiency()
        if egr is not None:
            out["egress_overlap_efficiency"] = egr.overlap_efficiency()
        for kind, n in self.faults.summary()["by_kind"].items():
            out[f"fault_{kind}_total"] = float(n)
        return out

    def stats(self) -> dict:
        """Superset of the reference's get_frame_stats (distributor.py:346-354)."""
        out = {
            **self.reorder.stats(),
            # (was total_frames_produced — renamed to the registry-
            # conformant counter form when the schema test landed)
            "frames_produced_total": self.frame_counter,
            "dropped_at_ingest": self.queue.dropped,
            "transport": type(self.queue).__name__,
            "errors": self.errors,
            "delivered": self.latency.count,
            "engine_batches": self.engine.stats.batches,
            # Classified fault counters + last-error records and the
            # number of supervisor engine rebuilds (resilience.faults) —
            # what a BENCH round asserts zero-unexpected-faults against.
            "faults": self.faults.summary(),
            "recoveries": self.recoveries,
            **self.latency.summary(),
        }
        if self._ingest_stats is not None:
            out["ingest"] = self._ingest_stats.summary()
        if self._egress_stats is not None:
            out["egress"] = self._egress_stats.summary()
        if self.config.chaos is not None:
            out["chaos"] = self.config.chaos.summary()
        return out
