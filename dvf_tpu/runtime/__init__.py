from dvf_tpu.runtime.egress import (  # noqa: F401
    AsyncCodecPlane,
    ShardedBatchFetcher,
)
from dvf_tpu.runtime.engine import Engine  # noqa: F401
from dvf_tpu.runtime.ingest import ShardedBatchAssembler  # noqa: F401
from dvf_tpu.runtime.pipeline import Pipeline, PipelineConfig  # noqa: F401
