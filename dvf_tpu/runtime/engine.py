"""Device engine: traced, batched, mesh-sharded filter execution.

This replaces the distributed hot path of the reference end-to-end
(SURVEY.md §3.3): everything between "ROUTER.send frame to worker" and
"PULL.recv result" (distributor.py:236-238 → worker.py:35-67 →
distributor.py:258-264) becomes

    device_put(batch)  →  one jitted sharded program  →  async fetch

Key TPU-first choices:
- **uint8 on the wire, both directions.** Frames cross host↔device as
  uint8 NHWC (¼ the bytes of float32 — PCIe/ICI bandwidth is the scarce
  resource, SURVEY.md §7 hard part 1). The cast to the filter's compute
  dtype happens on device, fused into the filter program.
- **Donation.** The input batch and filter state are donated, so steady
  state allocates nothing.
- **Async dispatch.** `submit` returns un-materialized `jax.Array`s; JAX's
  async dispatch pipelines host staging of batch k+1 under device compute
  of batch k — the double-buffering the reference approximates with
  threads+queues falls out of the runtime.
- **Static shapes.** One (batch, H, W, C) signature = one compilation;
  the assembler pads short batches (`valid` mask) rather than re-tracing.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.parallel.halo import spatial_filter
from dvf_tpu.parallel.mesh import batch_pspec, batch_sharding, make_mesh, replicated
from dvf_tpu.utils.image import to_float, to_uint8


# compile()-time D2H calibration is skipped above this output size — the
# one-time blocking fetch would dominate compile on a slow link (the
# tunneled bench chip moves ~20 MB/s D2H), and the signatures above it
# are the device-resident bench workloads that never stream egress.
_D2H_CALIBRATION_CAP_BYTES = 128 * 1024 * 1024


@dataclasses.dataclass
class EngineStats:
    batches: int = 0
    frames: int = 0
    compile_count: int = 0


class Engine:
    """Compiles and runs one filter over one mesh at one batch signature."""

    def __init__(
        self,
        filt: Filter,
        mesh: Optional[Mesh] = None,
        out_uint8: bool = True,
        chaos=None,
    ):
        self.filter = filt
        self.mesh = mesh if mesh is not None else make_mesh()
        self.out_uint8 = out_uint8
        self.chaos = chaos  # resilience.chaos.FaultPlan; armed test/replay
        #   runs only — submit paths fire the "oom"/"compute" injection
        #   sites through it (zero overhead when None)
        self.stats = EngineStats()
        self._exec_filter = filt   # possibly halo-wrapped in compile()
        self._step = None
        self._signature: Optional[Tuple] = None
        self._state: Any = None
        self._sharding = None  # chosen per batch signature in compile()
        self._replicated = replicated(self.mesh)
        self.h2d_block_ms: Optional[float] = None  # calibrated blocking
        #   whole-batch device_put at the compiled signature (measured on
        #   compile()'s warmup put) — the un-overlapped transfer cost the
        #   streamed ingest path's overlap_efficiency is judged against
        #   (obs.metrics.IngestStats)
        self.d2h_block_ms: Optional[float] = None  # the egress mirror:
        #   one blocking whole-batch materialization (np.asarray + copy
        #   into a host destination) of the warmup output — the
        #   serialized fetch cost the streamed egress path's
        #   overlap_efficiency is judged against (obs.metrics.EgressStats)
        self.out_shape: Optional[Tuple[int, ...]] = None  # compiled output
        self.out_dtype = None                             # signature — what
        #   the egress fetcher sizes its host slabs from (set by compile())
        self._out_sharding = None

    # ------------------------------------------------------------------

    def _pick_exec_filter(self, filt: Filter, batch_shape) -> "Filter":
        """Choose the executed filter + H-axis sharding for this signature.

        GSPMD's automatic spatial partitioning of stencil ops is distrusted
        on this toolchain (wrong halo values in some conv layouts), so an
        H-sharded mesh routes stencil filters through the EXPLICIT
        ppermute halo exchange (parallel.halo.spatial_filter). Pointwise
        filters (halo == 0) have no halo traffic and stay on plain GSPMD
        sharding. Filters that can't halo-exchange (stateful, unknown
        radius, slab thinner than the radius, indivisible H) keep H
        replicated — correct first, the inefficiency is logged.
        """
        pspec = batch_pspec(self.mesh, batch_shape)
        if pspec[1] != "space" or filt.halo == 0:
            # H unsharded, or pointwise (halo == 0): GSPMD is fine. A
            # pointwise filter needs no halo exchange even when stateful —
            # state placement is already handled by state_pspecs /
            # replication — so statefulness alone must not cost it H-axis
            # parallelism (or spam the can't-halo-shard warning).
            return filt
        n_space = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))["space"]
        can_halo = (
            not filt.stateful
            and filt.halo is not None
            and batch_shape[1] // n_space > filt.halo
        )
        if can_halo:
            return spatial_filter(
                filt, self.mesh, data_sharded=(pspec[0] == "data")
            )
        # Fall back to replicating H (shard batch only).
        print(
            f"[engine] filter {filt.name!r} can't halo-shard H "
            f"(stateful={filt.stateful}, halo={filt.halo}, "
            f"H={batch_shape[1]}, space={n_space}); replicating H",
            file=sys.stderr,
        )
        self._sharding = NamedSharding(self.mesh, P(pspec[0], None, None, None))
        return filt

    def _build_step(self, batch_shape, in_dtype):
        filt = self._exec_filter
        out_uint8 = self.out_uint8

        def step(batch, state):
            if batch.dtype == jnp.uint8 and not filt.uint8_ok:
                x = to_float(batch, filt.compute_dtype)
            else:
                x = batch
            y, new_state = filt.fn(x, state)
            if out_uint8 and y.dtype != jnp.uint8:
                y = to_uint8(y)
            return y, new_state

        # State placement: the filter's declared PartitionSpecs (neural
        # filters shard their weight pytree over 'model' — tensor
        # parallelism), else replicate (temporal state is small).
        state_shardings = self._state_shardings() if filt.stateful else None
        # Donate the input batch only when the output can actually reuse
        # its buffer — a geometry-changing filter (super_resolution) can't,
        # and XLA would warn "donated buffers were not usable" every run.
        out_aval = jax.eval_shape(
            step,
            jax.ShapeDtypeStruct(tuple(batch_shape), np.dtype(in_dtype)),
            self._state,  # built just before _build_step in compile()
        )[0]
        donate = ((0, 1)
                  if (out_aval.shape == tuple(batch_shape)
                      and out_aval.dtype == np.dtype(in_dtype))
                  else (1,))
        return jax.jit(
            step,
            in_shardings=(self._sharding, state_shardings),
            out_shardings=(self._sharding, state_shardings),
            donate_argnums=donate,
        )

    def _state_shardings(self):
        """Sharding (tree or single) for the state pytree; also valid as a
        jit in/out_shardings prefix and a device_put target."""
        if self._exec_filter.state_pspecs is not None:
            return jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                self._exec_filter.state_pspecs(),
                is_leaf=lambda x: isinstance(x, P),
            )
        return self._replicated

    def compile(self, batch_shape: Tuple[int, ...], dtype=np.uint8) -> None:
        """Trace + compile for a fixed (B,H,W,C) signature; builds state."""
        sig = (tuple(batch_shape), np.dtype(dtype))
        if sig == self._signature:
            return
        self._sharding = batch_sharding(self.mesh, batch_shape)
        # Mesh-aware body swap first (e.g. style transfer → shard_map'd
        # Megatron TP forward when the mesh has a model axis) …
        base = self.filter
        if base.specialize is not None:
            specialized = base.specialize(self.mesh, tuple(batch_shape))
            if specialized is not None:
                base = specialized
        # … then the H-axis halo routing — see _pick_exec_filter.
        self._exec_filter = self._pick_exec_filter(base, batch_shape)

        def fresh_state():
            ef = self._exec_filter
            if not ef.stateful:
                return None
            state_dtype = (
                ef.compute_dtype
                if np.dtype(dtype) == np.uint8 and not ef.uint8_ok
                else dtype
            )
            return jax.device_put(
                ef.init_state(batch_shape, state_dtype), self._state_shardings()
            )

        self._state = fresh_state()
        self._step = self._build_step(batch_shape, dtype)
        self._signature = sig
        self.stats.compile_count += 1
        # Warm the compile cache so the first real batch doesn't eat compile
        # time; the warmup consumes (donates) the state, so rebuild it —
        # stateful filters must still see a pristine first batch. A second
        # put at the same signature is the H2D calibration sample: one
        # blocking whole-batch transfer, measured AFTER the first put has
        # paid any backend/allocator warmup (timing the first put
        # over-reports the steady-state cost by an order of magnitude on
        # some backends, which would mislead the streamed-ingest
        # cheap-transfer fallback).
        zeros = np.zeros(batch_shape, dtype=dtype)
        warm = jax.device_put(zeros, self._sharding)
        jax.block_until_ready(warm)
        del warm
        t0 = time.perf_counter()
        dummy = jax.device_put(zeros, self._sharding)
        jax.block_until_ready(dummy)
        self.h2d_block_ms = (time.perf_counter() - t0) * 1e3
        out, _ = self._step(dummy, self._state)
        out.block_until_ready()
        # Output signature + sharding: what the egress fetcher lays its
        # per-shard host slabs out from (the mirror of input_sharding).
        self.out_shape = tuple(out.shape)
        self.out_dtype = np.dtype(out.dtype)
        self._out_sharding = out.sharding
        # D2H calibration: one blocking materialize-and-copy of the warmup
        # output — the serialized fetch the monolithic collect path pays
        # per batch. Unlike H2D there is no second-sample dance (jax
        # caches the first np.asarray, so a re-measure would clock a
        # cached view); the host destination is pre-touched so allocator
        # warmup stays out of the number. Skipped above the size cap: on
        # the tunneled bench chip a 400 MB batch-64 warmup fetch would
        # cost ~20 s of compile budget for a signature the egress path
        # never streams (device-resident benches fetch checksums only).
        if out.nbytes <= _D2H_CALIBRATION_CAP_BYTES:
            dst = np.empty(out.shape, out.dtype)
            dst.fill(0)
            t0 = time.perf_counter()
            np.copyto(dst, np.asarray(out))
            self.d2h_block_ms = (time.perf_counter() - t0) * 1e3
            del dst
        else:
            self.d2h_block_ms = None
        self._state = fresh_state()

    # ------------------------------------------------------------------

    def ensure_compiled(self, batch_shape: Tuple[int, ...],
                        dtype=np.uint8) -> None:
        """Compile for a signature if not already (idempotent) — the
        streamed-ingest assembler calls this before reading
        ``input_sharding`` to lay out its per-shard staging slabs."""
        self.compile(tuple(batch_shape), dtype)

    @property
    def signature(self) -> Optional[Tuple]:
        """The compiled ``((B, H, W, C), dtype)`` signature, or None
        before the first compile — what the serving frontend's
        admission-time geometry check compares a declared stream shape
        against (serve.ServeFrontend.open_stream)."""
        return self._signature

    @property
    def input_sharding(self):
        """The batch sharding the compiled step actually expects (set by
        compile(); may differ from the naive batch_sharding when the
        halo router replicated H). None before the first compile."""
        return self._sharding

    @property
    def output_sharding(self):
        """The compiled step's OUTPUT sharding (taken from the warmup
        result) — what the egress fetcher derives its per-shard fetch
        layout from. None before the first compile."""
        return self._out_sharding

    def submit(self, batch: np.ndarray) -> jax.Array:
        """Dispatch one host batch; returns the (async) on-device result.

        The filter state (if any) is threaded internally across calls —
        device-resident, never copied to host (SURVEY.md §7 hard part 4).
        """
        if self._signature != (tuple(batch.shape), np.dtype(batch.dtype)):
            self.compile(batch.shape, batch.dtype)
        if self.chaos is not None:
            self.chaos.fire("oom")
            self.chaos.fire("compute")
        x = jax.device_put(batch, self._sharding)
        y, self._state = self._step(x, self._state)
        self.stats.batches += 1
        self.stats.frames += batch.shape[0]
        return y

    def submit_resident(self, batch: jax.Array) -> jax.Array:
        """Serving entry for an already-device-resident batch: the
        streamed ingest path (runtime/ingest.py) shipped the shards while
        they decoded and assembled the mesh array itself, so the internal
        ``device_put`` of :meth:`submit` is skipped — the transfer cost
        it would serialize here was already hidden under decode and the
        previous batch's compute. State threading, donation, and stats
        are identical to :meth:`submit`.
        """
        if self._signature != (tuple(batch.shape), np.dtype(batch.dtype)):
            self.compile(batch.shape, np.dtype(batch.dtype))
        if self.chaos is not None:
            self.chaos.fire("oom")
            self.chaos.fire("compute")
        y, self._state = self._step(batch, self._state)
        self.stats.batches += 1
        self.stats.frames += batch.shape[0]
        return y

    def run_device_resident(self, batch: jax.Array) -> jax.Array:
        """Alias of :meth:`submit_resident` kept for the benchmark inner
        loops, which predate the serving-path name."""
        return self.submit_resident(batch)

    def cost_analysis(self) -> Optional[dict]:
        """XLA's own cost model for the compiled step: total FLOPs and HBM
        bytes accessed per batch. This is what the per-config roofline
        fractions in the bench tables are computed from — the compiler's
        estimate of traffic/arithmetic, not a hand-counted model, so fusion
        (e.g. the cast folded into the filter) is accounted for. Returns
        None when the backend doesn't implement cost analysis.

        Cost note: lower().compile() builds a second executable beside the
        jit-cached one, but every bench entry point sets
        JAX_COMPILATION_CACHE_DIR (cli._force_platform / bench_child), so
        for any program whose compile exceeded ~1 s this is a persistent-
        cache hit (deserialize, not recompile)."""
        if self._step is None or self._signature is None:
            return None
        shape, dtype = self._signature
        try:
            lowered = self._step.lower(
                jax.ShapeDtypeStruct(shape, dtype), self._state)
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax returns [dict]
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0))
            byts = float(ca.get("bytes accessed", 0.0))
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None
        if not (flops or byts):
            return None
        return {"flops_per_batch": flops, "bytes_accessed_per_batch": byts}

    def rebuild(self) -> "Engine":
        """Fresh engine for supervised recovery (resilience.supervisor):
        same filter/mesh/options, recompiled at the old signature — the
        full compile() path, so the replacement is re-warmed and its
        ``h2d_block_ms`` re-calibrated before it takes traffic. A
        stateful filter's temporal state restarts fresh (the wedged
        engine's device-resident state is unrecoverable by definition).
        """
        fresh = Engine(self.filter, mesh=self.mesh, out_uint8=self.out_uint8,
                       chaos=self.chaos)
        if self._signature is not None:
            shape, dtype = self._signature
            fresh.compile(shape, dtype)
        return fresh

    def reset_state(self) -> None:
        if self._exec_filter.stateful and self._signature is not None:
            shape, dtype = self._signature
            ef = self._exec_filter
            state_dtype = (
                ef.compute_dtype
                if dtype == np.uint8 and not ef.uint8_ok
                else dtype
            )
            self._state = jax.device_put(
                ef.init_state(shape, state_dtype), self._state_shardings()
            )
