"""Device engine: traced, batched, mesh-sharded filter execution.

This replaces the distributed hot path of the reference end-to-end
(SURVEY.md §3.3): everything between "ROUTER.send frame to worker" and
"PULL.recv result" (distributor.py:236-238 → worker.py:35-67 →
distributor.py:258-264) becomes

    device_put(batch)  →  one jitted sharded program  →  async fetch

Key TPU-first choices:
- **uint8 on the wire, both directions.** Frames cross host↔device as
  uint8 NHWC (¼ the bytes of float32 — PCIe/ICI bandwidth is the scarce
  resource, SURVEY.md §7 hard part 1). The cast to the filter's compute
  dtype happens on device, fused into the filter program.
- **Donation.** The input batch and filter state are donated, so steady
  state allocates nothing.
- **Async dispatch.** `submit` returns un-materialized `jax.Array`s; JAX's
  async dispatch pipelines host staging of batch k+1 under device compute
  of batch k — the double-buffering the reference approximates with
  threads+queues falls out of the runtime.
- **Static shapes.** One (batch, H, W, C) signature = one compilation;
  the assembler pads short batches (`valid` mask) rather than re-tracing.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dvf_tpu.api.filter import Filter
from dvf_tpu.parallel.halo import spatial_filter
from dvf_tpu.parallel.mesh import batch_pspec, batch_sharding, make_mesh, replicated
from dvf_tpu.utils.image import to_float, to_uint8


# compile()-time D2H calibration is skipped above this output size — the
# one-time blocking fetch would dominate compile on a slow link (the
# tunneled bench chip moves ~20 MB/s D2H), and the signatures above it
# are the device-resident bench workloads that never stream egress.
_D2H_CALIBRATION_CAP_BYTES = 128 * 1024 * 1024


@dataclasses.dataclass
class EngineStats:
    batches: int = 0
    frames: int = 0
    compile_count: int = 0


class Engine:
    """Compiles and runs one filter over one mesh at one batch signature."""

    def __init__(
        self,
        filt: Filter,
        mesh: Optional[Mesh] = None,
        out_uint8: bool = True,
        chaos=None,
        op_chain: Optional[str] = None,
        calibration_seed: Optional[dict] = None,
    ):
        self.filter = filt
        self.mesh = mesh if mesh is not None else make_mesh()
        self.out_uint8 = out_uint8
        self.op_chain = op_chain if op_chain is not None else filt.name
        #   the signature-key spelling of what this engine computes
        #   (runtime.signature.canonical_op_chain where parseable) —
        #   what the compiled-program pool and the multi-signature
        #   frontend key this engine by
        self.freed = False  # set by free(): device buffers released,
        #   submit is a programming error afterwards
        self.chaos = chaos  # resilience.chaos.FaultPlan; armed test/replay
        #   runs only — submit paths fire the "oom"/"compute" injection
        #   sites through it (zero overhead when None)
        self.stats = EngineStats()
        self._exec_filter = filt   # possibly halo-wrapped in compile()
        self._step = None
        self._signature: Optional[Tuple] = None
        self._state: Any = None
        self._sharding = None  # chosen per batch signature in compile()
        self._replicated = replicated(self.mesh)
        self.calibration_seed = calibration_seed  # optional persisted
        #   {h2d_block_ms, d2h_block_ms, step_block_ms} triple (plan
        #   cache, keyed per backend+topology — control.plan_cache):
        #   when present AND it carries real h2d+step numbers, compile()
        #   adopts it and SKIPS the blocking re-measurement passes — a
        #   warm restart pays trace+compile+warmup only. d2h may be
        #   None in a valid seed (measured above the calibration cap).
        self.calibration_seeded = False  # did the last compile() adopt
        #   the seed (vs measure)? — what the ledger's compile events
        #   record so warm-start behavior is auditable
        self.h2d_block_ms: Optional[float] = None  # calibrated blocking
        #   whole-batch device_put at the compiled signature (measured on
        #   compile()'s warmup put) — the un-overlapped transfer cost the
        #   streamed ingest path's overlap_efficiency is judged against
        #   (obs.metrics.IngestStats)
        self.d2h_block_ms: Optional[float] = None  # the egress mirror:
        #   one blocking whole-batch materialization (np.asarray + copy
        #   into a host destination) of the warmup output — the
        #   serialized fetch cost the streamed egress path's
        #   overlap_efficiency is judged against (obs.metrics.EgressStats)
        self.step_block_ms: Optional[float] = None  # calibrated blocking
        #   execution of ONE compiled step at the signature (measured on
        #   a post-warmup run in compile(), so trace/compile time stays
        #   out of it) — the MEASURED per-batch tick cost the bucket
        #   scheduler's EDF/cost score starts from before it has live
        #   samples (TVM's measured-stage discipline: pick costs from
        #   measurements, not guesses). Skipped (None) above the
        #   calibration size cap.
        self.out_shape: Optional[Tuple[int, ...]] = None  # compiled output
        self.out_dtype = None                             # signature — what
        #   the egress fetcher sizes its host slabs from (set by compile())
        self._out_sharding = None
        self.last_compile_ms: Optional[float] = None  # wall duration of
        #   the most recent compile() (trace + XLA compile + warmup +
        #   calibrations — the whole admission-visible cost): what the
        #   reconfiguration ledger's compile events and the
        #   dvf_compile_ms histogram record
        self.state_bytes: int = 0  # measured device residency of the
        #   filter state (summed leaf nbytes at compile) — the per-
        #   engine half of the memory accounting; free() folds it into
        #   the process-wide freed counter
        # Double-buffered program swap (stall-free reconfiguration):
        # prepare_swap() compiles a successor engine ASIDE (background
        # thread, nothing blocked), commit_swap() adopts its program
        # fields in place between ticks. The lock serializes staging
        # bookkeeping and the commit's field swing against run_probe
        # (the audit worker must never read a half-adopted program).
        self._swap_lock = threading.RLock()
        self._staged: Optional["Engine"] = None
        self._preparing: Dict[Tuple, threading.Event] = {}
        self.swap_count = 0
        self.last_swap: Optional[dict] = None

    # ------------------------------------------------------------------

    def _pick_exec_filter(self, filt: Filter, batch_shape) -> "Filter":
        """Choose the executed filter + H-axis sharding for this signature.

        GSPMD's automatic spatial partitioning of stencil ops is distrusted
        on this toolchain (wrong halo values in some conv layouts), so an
        H-sharded mesh routes stencil filters through the EXPLICIT
        ppermute halo exchange (parallel.halo.spatial_filter). Pointwise
        filters (halo == 0) have no halo traffic and stay on plain GSPMD
        sharding. Filters that can't halo-exchange (stateful, unknown
        radius, slab thinner than the radius, indivisible H) keep H
        replicated — correct first, the inefficiency is logged.
        """
        pspec = batch_pspec(self.mesh, batch_shape)
        if pspec[1] != "space" or filt.halo == 0:
            # H unsharded, or pointwise (halo == 0): GSPMD is fine. A
            # pointwise filter needs no halo exchange even when stateful —
            # state placement is already handled by state_pspecs /
            # replication — so statefulness alone must not cost it H-axis
            # parallelism (or spam the can't-halo-shard warning).
            return filt
        n_space = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))["space"]
        can_halo = (
            not filt.stateful
            and filt.halo is not None
            and batch_shape[1] // n_space > filt.halo
        )
        if can_halo:
            return spatial_filter(
                filt, self.mesh, data_sharded=(pspec[0] == "data")
            )
        # Fall back to replicating H (shard batch only).
        print(
            f"[engine] filter {filt.name!r} can't halo-shard H "
            f"(stateful={filt.stateful}, halo={filt.halo}, "
            f"H={batch_shape[1]}, space={n_space}); replicating H",
            file=sys.stderr,
        )
        self._sharding = NamedSharding(self.mesh, P(pspec[0], None, None, None))
        return filt

    def _build_step(self, batch_shape, in_dtype):
        filt = self._exec_filter
        out_uint8 = self.out_uint8

        def step(batch, state):
            if batch.dtype == jnp.uint8 and not filt.uint8_ok:
                x = to_float(batch, filt.compute_dtype)
            else:
                x = batch
            y, new_state = filt.fn(x, state)
            if out_uint8 and y.dtype != jnp.uint8:
                y = to_uint8(y)
            return y, new_state

        # State placement: the filter's declared PartitionSpecs (neural
        # filters shard their weight pytree over 'model' — tensor
        # parallelism), else replicate (temporal state is small).
        state_shardings = self._state_shardings() if filt.stateful else None
        # Donate the input batch only when the output can actually reuse
        # its buffer — a geometry-changing filter (super_resolution) can't,
        # and XLA would warn "donated buffers were not usable" every run.
        out_aval = jax.eval_shape(
            step,
            jax.ShapeDtypeStruct(tuple(batch_shape), np.dtype(in_dtype)),
            self._state,  # built just before _build_step in compile()
        )[0]
        donate = ((0, 1)
                  if (out_aval.shape == tuple(batch_shape)
                      and out_aval.dtype == np.dtype(in_dtype))
                  else (1,))
        return jax.jit(
            step,
            in_shardings=(self._sharding, state_shardings),
            out_shardings=(self._sharding, state_shardings),
            donate_argnums=donate,
        )

    def _state_shardings(self):
        """Sharding (tree or single) for the state pytree; also valid as a
        jit in/out_shardings prefix and a device_put target."""
        if self._exec_filter.state_pspecs is not None:
            return jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                self._exec_filter.state_pspecs(),
                is_leaf=lambda x: isinstance(x, P),
            )
        return self._replicated

    def compile(self, batch_shape: Tuple[int, ...], dtype=np.uint8) -> None:
        """Trace + compile for a fixed (B,H,W,C) signature; builds state."""
        sig = (tuple(batch_shape), np.dtype(dtype))
        if sig == self._signature:
            return
        t_compile0 = time.perf_counter()
        self._sharding = batch_sharding(self.mesh, batch_shape)
        # Mesh-aware body swap first (e.g. style transfer → shard_map'd
        # Megatron TP forward when the mesh has a model axis) …
        base = self.filter
        if base.specialize is not None:
            specialized = base.specialize(self.mesh, tuple(batch_shape))
            if specialized is not None:
                base = specialized
        # … then the H-axis halo routing — see _pick_exec_filter.
        self._exec_filter = self._pick_exec_filter(base, batch_shape)

        def fresh_state():
            ef = self._exec_filter
            if not ef.stateful:
                return None
            state_dtype = (
                ef.compute_dtype
                if np.dtype(dtype) == np.uint8 and not ef.uint8_ok
                else dtype
            )
            return jax.device_put(
                ef.init_state(batch_shape, state_dtype), self._state_shardings()
            )

        self._state = fresh_state()
        self._step = self._build_step(batch_shape, dtype)
        self._signature = sig
        self.stats.compile_count += 1
        # Warm the compile cache so the first real batch doesn't eat compile
        # time; the warmup consumes (donates) the state, so rebuild it —
        # stateful filters must still see a pristine first batch. A second
        # put at the same signature is the H2D calibration sample: one
        # blocking whole-batch transfer, measured AFTER the first put has
        # paid any backend/allocator warmup (timing the first put
        # over-reports the steady-state cost by an order of magnitude on
        # some backends, which would mislead the streamed-ingest
        # cheap-transfer fallback).
        zeros = np.zeros(batch_shape, dtype=dtype)
        warm = jax.device_put(zeros, self._sharding)
        jax.block_until_ready(warm)
        # Persisted-calibration fast path (auto-plan plane): a seed with
        # real h2d+step numbers — measured earlier on this same
        # backend+topology and loaded from the plan cache — replaces
        # every timed pass below. The warmup put and warmup step still
        # run (they ARE the compile warm + output-signature discovery);
        # what a warm restart skips is the blocking measurement choreo:
        # the second put, the whole-batch D2H copy, and the extra
        # donated step with its two state rebuilds.
        seed = self.calibration_seed
        seeded = (isinstance(seed, dict)
                  and isinstance(seed.get("h2d_block_ms"), (int, float))
                  and isinstance(seed.get("step_block_ms"), (int, float)))
        self.calibration_seeded = seeded
        if seeded:
            self.h2d_block_ms = float(seed["h2d_block_ms"])
            dummy = warm
        else:
            del warm
            t0 = time.perf_counter()
            dummy = jax.device_put(zeros, self._sharding)
            jax.block_until_ready(dummy)
            self.h2d_block_ms = (time.perf_counter() - t0) * 1e3
        out, _ = self._step(dummy, self._state)
        out.block_until_ready()
        # Output signature + sharding: what the egress fetcher lays its
        # per-shard host slabs out from (the mirror of input_sharding).
        self.out_shape = tuple(out.shape)
        self.out_dtype = np.dtype(out.dtype)
        self._out_sharding = out.sharding
        # D2H calibration: one blocking materialize-and-copy of the warmup
        # output — the serialized fetch the monolithic collect path pays
        # per batch. Unlike H2D there is no second-sample dance (jax
        # caches the first np.asarray, so a re-measure would clock a
        # cached view); the host destination is pre-touched so allocator
        # warmup stays out of the number. Skipped above the size cap: on
        # the tunneled bench chip a 400 MB batch-64 warmup fetch would
        # cost ~20 s of compile budget for a signature the egress path
        # never streams (device-resident benches fetch checksums only).
        if seeded:
            # d2h may legitimately be None in a valid seed (the original
            # measurement was above the calibration cap) — reproduce it.
            d2h = seed.get("d2h_block_ms")
            self.d2h_block_ms = (float(d2h)
                                 if isinstance(d2h, (int, float)) else None)
        elif out.nbytes <= _D2H_CALIBRATION_CAP_BYTES:
            dst = np.empty(out.shape, out.dtype)
            dst.fill(0)
            t0 = time.perf_counter()
            np.copyto(dst, np.asarray(out))
            self.d2h_block_ms = (time.perf_counter() - t0) * 1e3
            del dst
        else:
            self.d2h_block_ms = None
        self._state = fresh_state()
        # Tick-cost calibration: one more blocking step, AFTER the warmup
        # compiled it — a measured per-batch execution cost for the
        # multi-signature bucket scheduler (its EDF/cost score needs a
        # starting estimate before live ticks arrive; guessing would let
        # a cheap bucket starve behind an expensive one). The step
        # donates its operands, so state is rebuilt once more. Skipped
        # above the calibration cap for the same reason D2H is.
        if seeded:
            self.step_block_ms = float(seed["step_block_ms"])
        elif zeros.nbytes <= _D2H_CALIBRATION_CAP_BYTES:
            cal = jax.device_put(zeros, self._sharding)
            t0 = time.perf_counter()
            out2, _ = self._step(cal, self._state)
            out2.block_until_ready()
            self.step_block_ms = (time.perf_counter() - t0) * 1e3
            del cal, out2
            self._state = fresh_state()
        else:
            self.step_block_ms = None
        self.last_compile_ms = (time.perf_counter() - t_compile0) * 1e3
        self.state_bytes = _tree_device_bytes(self._state)

    # ------------------------------------------------------------------

    def ensure_compiled(self, batch_shape: Tuple[int, ...],
                        dtype=np.uint8) -> None:
        """Compile for a signature if not already (idempotent) — the
        streamed-ingest assembler calls this before reading
        ``input_sharding`` to lay out its per-shard staging slabs."""
        self.compile(tuple(batch_shape), dtype)

    @property
    def signature(self) -> Optional[Tuple]:
        """The compiled ``((B, H, W, C), dtype)`` signature, or None
        before the first compile — what the serving frontend's
        admission-time geometry check compares a declared stream shape
        against (serve.ServeFrontend.open_stream)."""
        return self._signature

    @property
    def signature_key(self):
        """The CANONICAL ``(op_chain, geometry, dtype)`` serving
        signature (runtime.signature.SignatureKey) — dtype and geometry
        spellings normalized so equal programs can't miss the
        compiled-program pool or the persistent compilation cache by
        spelling. None before the first compile."""
        from dvf_tpu.runtime.signature import engine_signature_key

        return engine_signature_key(self)

    @property
    def input_sharding(self):
        """The batch sharding the compiled step actually expects (set by
        compile(); may differ from the naive batch_sharding when the
        halo router replicated H). None before the first compile."""
        return self._sharding

    @property
    def output_sharding(self):
        """The compiled step's OUTPUT sharding (taken from the warmup
        result) — what the egress fetcher derives its per-shard fetch
        layout from. None before the first compile."""
        return self._out_sharding

    def submit(self, batch: np.ndarray) -> jax.Array:
        """Dispatch one host batch; returns the (async) on-device result.

        The filter state (if any) is threaded internally across calls —
        device-resident, never copied to host (SURVEY.md §7 hard part 4).
        """
        if self.freed:
            raise RuntimeError(
                "engine was freed (program-pool eviction); re-admission "
                "builds a fresh engine through the pool")
        if self._signature != (tuple(batch.shape), np.dtype(batch.dtype)):
            self.compile(batch.shape, batch.dtype)
        if self.chaos is not None:
            self.chaos.fire("oom")
            self.chaos.fire("compute")
        x = jax.device_put(batch, self._sharding)
        y, self._state = self._step(x, self._state)
        self.stats.batches += 1
        self.stats.frames += batch.shape[0]
        return y

    def submit_resident(self, batch: jax.Array) -> jax.Array:
        """Serving entry for an already-device-resident batch: the
        streamed ingest path (runtime/ingest.py) shipped the shards while
        they decoded and assembled the mesh array itself, so the internal
        ``device_put`` of :meth:`submit` is skipped — the transfer cost
        it would serialize here was already hidden under decode and the
        previous batch's compute. State threading, donation, and stats
        are identical to :meth:`submit`.
        """
        if self.freed:
            raise RuntimeError(
                "engine was freed (program-pool eviction); re-admission "
                "builds a fresh engine through the pool")
        if self._signature != (tuple(batch.shape), np.dtype(batch.dtype)):
            self.compile(batch.shape, np.dtype(batch.dtype))
        if self.chaos is not None:
            self.chaos.fire("oom")
            self.chaos.fire("compute")
        y, self._state = self._step(batch, self._state)
        self.stats.batches += 1
        self.stats.frames += batch.shape[0]
        return y

    def run_device_resident(self, batch: jax.Array) -> jax.Array:
        """Alias of :meth:`submit_resident` kept for the benchmark inner
        loops, which predate the serving-path name."""
        return self.submit_resident(batch)

    def run_probe(self, batch: np.ndarray) -> np.ndarray:
        """Audit-plane probe entry (obs.audit): run the compiled step on
        ``batch`` WITHOUT touching serving state or stats — no state
        threading (the returned state is discarded; stateless filters
        only, where it is None anyway), no batch/frame counters, no
        chaos sites. Safe to call concurrently with the serving
        dispatch: jitted executables are thread-safe and the probe's
        operands are its own fresh device buffers. Blocking
        (materializes the result) — callers are off the hot path by
        contract (swap guards, divergence probes)."""
        # Under the swap lock: commit_swap swings every program field
        # as one atomic update, and a probe racing it must read either
        # the old program wholesale or the new one — never a mix.
        with self._swap_lock:
            if self.freed:
                raise RuntimeError("cannot probe a freed engine")
            if self._step is None or self._signature is None:
                raise RuntimeError("cannot probe an uncompiled engine")
            if self._exec_filter.stateful:
                raise ValueError(
                    f"cannot probe stateful filter {self.filter.name!r}: "
                    f"the probe would consume (donated) live temporal "
                    f"state")
            if (tuple(batch.shape),
                    np.dtype(batch.dtype)) != self._signature:
                raise ValueError(
                    f"probe batch {batch.shape}/{batch.dtype} does not "
                    f"match the compiled signature {self._signature}")
            x = jax.device_put(np.ascontiguousarray(batch),
                               self._sharding)
            step, state = self._step, self._state
        y, _ = step(x, state)
        return np.asarray(y)

    def cost_analysis(self) -> Optional[dict]:
        """XLA's own cost model for the compiled step: total FLOPs and HBM
        bytes accessed per batch. This is what the per-config roofline
        fractions in the bench tables are computed from — the compiler's
        estimate of traffic/arithmetic, not a hand-counted model, so fusion
        (e.g. the cast folded into the filter) is accounted for. Returns
        None when the backend doesn't implement cost analysis.

        Cost note: lower().compile() builds a second executable beside the
        jit-cached one, but every bench entry point sets
        JAX_COMPILATION_CACHE_DIR (cli._force_platform / bench_child), so
        for any program whose compile exceeded ~1 s this is a persistent-
        cache hit (deserialize, not recompile)."""
        if self._step is None or self._signature is None:
            return None
        shape, dtype = self._signature
        try:
            lowered = self._step.lower(
                jax.ShapeDtypeStruct(shape, dtype), self._state)
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax returns [dict]
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0))
            byts = float(ca.get("bytes accessed", 0.0))
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            return None
        if not (flops or byts):
            return None
        return {"flops_per_batch": flops, "bytes_accessed_per_batch": byts}

    def rebuild(self) -> "Engine":
        """Fresh engine for supervised recovery (resilience.supervisor):
        same filter/mesh/options, recompiled at the old signature — the
        full compile() path, so the replacement is re-warmed and its
        ``h2d_block_ms`` re-calibrated before it takes traffic. A
        stateful filter's temporal state restarts fresh (the wedged
        engine's device-resident state is unrecoverable by definition).
        """
        fresh = Engine(self.filter, mesh=self.mesh, out_uint8=self.out_uint8,
                       chaos=self.chaos, op_chain=self.op_chain)
        if self._signature is not None:
            shape, dtype = self._signature
            fresh.compile(shape, dtype)
        return fresh

    # -- double-buffered hot swap (stall-free reconfiguration) ----------

    def prepare_swap(self, batch_shape: Tuple[int, ...], dtype=np.uint8,
                     force: bool = False) -> dict:
        """Compile the successor program for ``batch_shape``/``dtype``
        ASIDE — a fresh engine traced, compiled, warmed, and calibrated
        on THIS (background) thread while the live program keeps
        serving. Nothing the serving path reads is touched until
        :meth:`commit_swap` adopts the staged successor between ticks.

        ``force=True`` prepares even at the live signature (a fresh
        program + fresh state at the same shape — the supervised-
        recovery rebuild, compiled aside instead of in place).

        Concurrent prepares for the same successor signature dedup onto
        one compile via a per-signature latch (the engine-level mirror
        of ``ProgramPool.acquire``'s per-key latch); a prepare for a
        DIFFERENT signature supersedes the previously staged successor
        (its buffers are freed — last prepare wins).

        Returns ``{"compile_aside_ms", "staged", "cache"}``; ``staged``
        False means the live program already serves this signature and
        nothing was built. Raises on compile failure (and on the chaos
        ``swap`` site) with the live program untouched.
        """
        if self.freed:
            raise RuntimeError("cannot prepare a swap on a freed engine")
        sig = (tuple(batch_shape), np.dtype(dtype))
        if sig == self._signature and not force:
            return {"compile_aside_ms": 0.0, "staged": False,
                    "cache": "live"}
        while True:
            with self._swap_lock:
                st = self._staged
                if st is not None and st._signature == sig and not force:
                    return {"compile_aside_ms": 0.0, "staged": True,
                            "cache": "staged"}
                latch = self._preparing.get(sig)
                if latch is None:
                    self._preparing[sig] = latch = threading.Event()
                    break
            # Another thread is building this successor: wait it out,
            # then re-check (it staged the program, or died and we
            # build).
            latch.wait(timeout=300.0)
        t0 = time.perf_counter()
        try:
            if self.chaos is not None:
                self.chaos.fire("swap")  # injection site: aside-compile
                #   failure — the old program must keep serving
            succ = Engine(self.filter, mesh=self.mesh,
                          out_uint8=self.out_uint8, chaos=self.chaos,
                          op_chain=self.op_chain)
            succ.compile(tuple(batch_shape), dtype)
        except BaseException:
            with self._swap_lock:
                self._preparing.pop(sig, None)
            latch.set()
            raise
        ms = (time.perf_counter() - t0) * 1e3
        with self._swap_lock:
            old, self._staged = self._staged, succ
            self._preparing.pop(sig, None)
        latch.set()
        if old is not None and old is not succ:
            old.free()  # superseded staging
        return {"compile_aside_ms": ms, "staged": True, "cache": "miss"}

    @property
    def swap_staged(self) -> bool:
        """Whether a prepared successor is waiting for commit_swap."""
        with self._swap_lock:
            return self._staged is not None

    def commit_swap(self, migrate_state: bool = True) -> dict:
        """Adopt the staged successor program atomically: ONE lock-
        guarded field swing — call from the thread that owns submits
        (the serving dispatch thread), so a batch never straddles the
        old and new programs. In-flight batches already submitted on
        the old program hold their own result references and drain
        normally; the old program's handles drop here and its buffers
        free once they do.

        Device-resident filter state migrates device-to-device when the
        successor's state tree matches shape-for-shape
        (``migrate_state=True``); a geometry-changing swap (or
        ``migrate_state=False`` — supervised recovery, whose old state
        is poisoned by definition) keeps the successor's fresh state.

        Returns ``{"migrate_ms", "stall_ms", "migrated"}`` — stall_ms
        is the measured wall duration of this call, the ONLY serving
        time the swap consumes. Raises (chaos ``swap`` site mid-
        migrate, a failed device copy) with the live program untouched
        and the staged successor freed: a failed swap leaves the old
        program serving.
        """
        with self._swap_lock:
            succ = self._staged
            if succ is None:
                raise RuntimeError(
                    "no staged successor program (prepare_swap first)")
            self._staged = None
            t0 = time.perf_counter()
            migrate_ms = 0.0
            migrated = False
            try:
                if self.chaos is not None:
                    self.chaos.fire("swap")  # injection site: mid-
                    #   migrate failure — abort, old program serving
                if migrate_state and self._exec_filter.stateful \
                        and self._state is not None \
                        and succ._exec_filter.stateful:
                    t_m = time.perf_counter()
                    migrated = self._migrate_state_to(succ)
                    if migrated:
                        migrate_ms = (time.perf_counter() - t_m) * 1e3
            except BaseException:
                succ.free()
                raise
            # The swing: adopt every program field the serving/egress
            # paths read. In place — the engine OBJECT survives, so
            # pool leases, bucket bindings, and probe callers keep one
            # stable identity across any number of swaps.
            for name in ("_step", "_signature", "_state", "_sharding",
                         "_exec_filter", "out_shape", "out_dtype",
                         "_out_sharding", "h2d_block_ms", "d2h_block_ms",
                         "step_block_ms", "last_compile_ms",
                         "state_bytes"):
                setattr(self, name, getattr(succ, name))
            self.stats.compile_count += succ.stats.compile_count
            # Neuter the successor shell: its device buffers now belong
            # to this engine — its free() must not free them.
            succ._step = None
            succ._state = None
            succ._sharding = None
            succ._out_sharding = None
            succ.state_bytes = 0
            succ.freed = True
            self.swap_count += 1
            stall_ms = (time.perf_counter() - t0) * 1e3
            self.last_swap = {"migrate_ms": round(migrate_ms, 3),
                              "stall_ms": round(stall_ms, 3),
                              "migrated": migrated}
            return dict(self.last_swap)

    def _migrate_state_to(self, succ: "Engine") -> bool:
        """Device-to-device re-placement of the live filter state under
        the successor's shardings — only when the trees match leaf-for-
        leaf (same structure, shapes, dtypes). False = shapes diverged
        (the successor keeps its fresh init state; a geometry change
        resets temporal state by definition)."""
        old_leaves = jax.tree_util.tree_leaves(self._state)
        new_leaves = jax.tree_util.tree_leaves(succ._state)
        if len(old_leaves) != len(new_leaves):
            return False
        for a, b in zip(old_leaves, new_leaves):
            if (tuple(getattr(a, "shape", ())) != tuple(
                    getattr(b, "shape", ()))
                    or getattr(a, "dtype", None) != getattr(b, "dtype",
                                                            None)):
                return False
        succ._state = jax.device_put(self._state,
                                     succ._state_shardings())
        jax.block_until_ready(succ._state)
        return True

    def abort_swap(self) -> bool:
        """Free a staged successor without adopting it (the owner
        decided against the swap, or its commit precondition failed).
        True when something was staged."""
        with self._swap_lock:
            succ, self._staged = self._staged, None
        if succ is not None:
            succ.free()
            return True
        return False

    def free(self) -> None:
        """Release this engine's device residency: the compiled program
        handle, the device-resident state, and the warmup-derived
        sharding refs are dropped so XLA can reclaim the buffers — the
        compiled-program pool's eviction path. Idempotent; a freed
        engine refuses further submits (re-admission goes through a
        FRESH engine so recompilation hits the persistent cache, it
        does not resurrect this object)."""
        if self.freed:
            return
        self.freed = True
        with self._swap_lock:
            staged, self._staged = self._staged, None
        if staged is not None:
            staged.free()  # an un-committed successor must not leak
        self._step = None
        self._state = None
        self._sharding = None
        self._out_sharding = None
        _note_freed_bytes(self.state_bytes)
        _unregister_pool_engine(self)

    def reset_state(self) -> None:
        if self._exec_filter.stateful and self._signature is not None:
            shape, dtype = self._signature
            ef = self._exec_filter
            state_dtype = (
                ef.compute_dtype
                if dtype == np.uint8 and not ef.uint8_ok
                else dtype
            )
            self._state = jax.device_put(
                ef.init_state(shape, state_dtype), self._state_shardings()
            )


# ---------------------------------------------------------------------------
# Compiled-program pool (multi-signature serving)
# ---------------------------------------------------------------------------

# Every engine currently holding device buffers under a ProgramPool's
# management. The conftest session-end guard walks this: a pool engine
# still live after every frontend closed means some stop() path stopped
# freeing — a long-lived multi-tenant server would leak one compiled
# program (plus its device state) per churned signature forever.
_POOL_ENGINES: "set" = set()
_POOL_ENGINES_LOCK = threading.Lock()

# Donated/freed device-memory accounting (obs.memory): Engine.free()
# folds the freed engine's measured state residency in here, so the
# scrape-time gauges can report eviction traffic as a monotone counter.
_FREED_DEVICE_BYTES = 0


def _note_freed_bytes(n: int) -> None:
    global _FREED_DEVICE_BYTES
    with _POOL_ENGINES_LOCK:
        _FREED_DEVICE_BYTES += int(n or 0)


def freed_device_bytes_total() -> int:
    """Monotone: device state bytes released by every ``Engine.free()``
    so far (pool evictions, frontend stops, recovery replacements) —
    the ``dvf_mem_engine_freed_bytes_total`` counter's source."""
    with _POOL_ENGINES_LOCK:
        return _FREED_DEVICE_BYTES


def _tree_device_bytes(state) -> int:
    """Summed leaf nbytes of a (possibly None) device-resident pytree —
    the engine's measured state residency."""
    if state is None:
        return 0
    try:
        return int(sum(
            int(getattr(leaf, "nbytes", 0) or 0)
            for leaf in jax.tree_util.tree_leaves(state)))
    except Exception:  # noqa: BLE001 — accounting must never raise
        return 0


def _register_pool_engine(engine: "Engine") -> None:
    with _POOL_ENGINES_LOCK:
        _POOL_ENGINES.add(engine)


def _unregister_pool_engine(engine: "Engine") -> None:
    with _POOL_ENGINES_LOCK:
        _POOL_ENGINES.discard(engine)


def live_pool_engines() -> List["Engine"]:
    """Pool-managed engines whose device buffers are still live — the
    conftest leak guard's registry (mirrors fleet.replica.
    live_worker_processes)."""
    with _POOL_ENGINES_LOCK:
        return [e for e in _POOL_ENGINES if not e.freed]


class ProgramPool:
    """Bounded LRU of live compiled Engines, keyed by canonical
    signature (runtime.signature.SignatureKey).

    N serving signatures time-share ONE device without N processes: a
    bucket *leases* its engine (refcounted — a leased program is never
    evicted out from under in-flight batches), releases it when the
    bucket retires, and the program stays WARM in the pool until LRU
    capacity pressure frees its device buffers (``Engine.free``).
    Re-admission of an evicted signature recompiles through ``build`` —
    with the persistent compilation cache armed
    (:func:`enable_compilation_cache`) that recompile is a cache
    deserialize, not a fresh XLA run.

    ``hits``/``misses``/``evictions`` are the ``dvf_compile_cache_*`` /
    pool-eviction registry exports.
    """

    def __init__(self, capacity: int = 8):
        if capacity < 1:
            raise ValueError("pool capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        # key -> [engine, lease_count]; OrderedDict gives LRU order.
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._building: Dict[Any, threading.Event] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.closed = False
        self.observer: Optional[Callable] = None  # reconfiguration-
        #   ledger tap (duck-typed: observer(kind, **fields)): the owner
        #   wires it to record pool_acquire / compile / pool_evict
        #   events. Always called OUTSIDE the pool lock; exceptions are
        #   swallowed — accounting must never break a lease.

    def _notify(self, kind: str, **fields) -> None:
        obs = self.observer
        if obs is None:
            return
        try:
            obs(kind, **fields)
        except Exception:  # noqa: BLE001 — see observer comment
            pass

    def acquire(self, key, build: Callable[[], "Engine"],
                cause: Optional[str] = None) -> "Engine":
        """Lease the engine for ``key``: LRU hit (warm — milliseconds)
        or ``build()`` (cold — trace/compile; runs OUTSIDE the pool lock
        so one slow compile can't block every other bucket's lease, with
        a per-key latch so concurrent admits of the same signature
        compile once). ``cause`` labels the ledger event (admission /
        quality / precompile / …)."""
        while True:
            with self._lock:
                if self.closed:
                    raise RuntimeError("program pool is closed")
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    ent[1] += 1
                    self.hits += 1
                    engine = ent[0]
                    break
                latch = self._building.get(key)
                if latch is None:
                    self._building[key] = latch = threading.Event()
                    engine = None
                    break
            latch.wait(timeout=300.0)  # builder finished (or died): re-check
        if engine is not None:
            self._notify("pool_acquire", cause=cause, key=key,
                         cache="hit", engine=engine)
            return engine
        t_build = time.perf_counter()
        try:
            engine = build()
        except BaseException:
            with self._lock:
                self._building.pop(key, None)
            latch.set()
            raise
        build_ms = (time.perf_counter() - t_build) * 1e3
        with self._lock:
            if self.closed:
                # close() raced the build: the pool's free sweep already
                # ran, so inserting now would leak a live program that
                # nothing ever frees. Refuse (below, outside the lock,
                # after freeing what we built).
                self._building.pop(key, None)
                raced_close = True
            else:
                raced_close = False
                self.misses += 1
                self._entries[key] = [engine, 1]
                _register_pool_engine(engine)
                self._building.pop(key, None)
                evicted = self._evict_over_capacity_locked()
        latch.set()
        if raced_close:
            engine.free()
            raise RuntimeError("program pool is closed")
        self._notify("compile", cause=cause, key=key, cache="miss",
                     wall_ms=build_ms, engine=engine)
        self._free_evicted(evicted)
        return engine

    def adopt(self, key, engine: "Engine") -> None:
        """Insert an externally built engine as a leased entry — how the
        frontend's default bucket (whose engine may be caller-built and
        predate its key being known) joins the pool once pinned.
        Raises RuntimeError on a closed pool (adopt racing the owner's
        stop must not insert a program the close sweep already missed)."""
        with self._lock:
            if self.closed:
                raise RuntimeError("program pool is closed")
            if key in self._entries:
                ent = self._entries[key]
                if ent[0] is engine:
                    return
                raise ValueError(f"pool already holds a different engine "
                                 f"for {key}")
            self._entries[key] = [engine, 1]
            self._entries.move_to_end(key)
            _register_pool_engine(engine)
            evicted = self._evict_over_capacity_locked()
        self._free_evicted(evicted)

    def release(self, key) -> None:
        """Drop one lease. The program STAYS warm (that is the point —
        the next admit of this signature is a pool hit) until capacity
        pressure evicts it."""
        evicted = []
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return
            ent[1] = max(0, ent[1] - 1)
            evicted = self._evict_over_capacity_locked()
        self._free_evicted(evicted)

    def replace(self, key, engine: "Engine") -> None:
        """Swap the live engine under an existing lease (supervised
        recovery rebuilt it); the old engine's buffers are freed. On a
        closed pool the rebuilt engine is freed and the call raises —
        a recovery racing the owner's stop() must not insert a program
        the close sweep already missed. A concurrently-retired key
        re-enters WARM (lease 0): nothing holds it, so capacity
        pressure may evict it immediately."""
        old = None
        evicted: List[Tuple[Any, "Engine"]] = []
        with self._lock:
            if self.closed:
                raced_close = True
            else:
                raced_close = False
                ent = self._entries.get(key)
                if ent is None:
                    self._entries[key] = [engine, 0]
                    _register_pool_engine(engine)
                    evicted = self._evict_over_capacity_locked()
                else:
                    old = ent[0]
                    ent[0] = engine
                    _register_pool_engine(engine)
        if raced_close:
            engine.free()
            raise RuntimeError("program pool is closed")
        self._free_evicted(evicted)
        if old is not None and old is not engine:
            old.free()

    def _evict_over_capacity_locked(self) -> List[Tuple[Any, "Engine"]]:
        """Pop LRU un-leased entries while over capacity; leased entries
        are skipped (a live program can't be freed under its bucket), so
        the pool may transiently exceed capacity when every entry is
        leased — bounded by the frontend's max_buckets. Returns
        ``(key, engine)`` pairs for the caller to free (and ledger)
        outside the lock."""
        out: List[Tuple[Any, "Engine"]] = []
        if len(self._entries) <= self.capacity:
            return out
        for key in list(self._entries):
            if len(self._entries) <= self.capacity:
                break
            if self._entries[key][1] == 0:
                out.append((key, self._entries.pop(key)[0]))
                self.evictions += 1
        return out

    def _free_evicted(self, evicted: List[Tuple[Any, "Engine"]]) -> None:
        for key, e in evicted:
            e.free()
            self._notify("pool_evict", cause="capacity", key=key,
                         engine=e)

    def evict(self, key) -> bool:
        """Explicitly drop one un-leased entry (tests; manual cache
        control). False when absent or still leased."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or ent[1] > 0:
                return False
            engine = self._entries.pop(key)[0]
            self.evictions += 1
        engine.free()
        self._notify("pool_evict", cause="manual", key=key, engine=engine)
        return True

    def warm_keys(self) -> List:
        """Signatures this pool can serve without a compile — what
        admission-rejection messages enumerate and the fleet's
        warm-replica preference matches against."""
        with self._lock:
            return list(self._entries)

    def peek(self, key) -> Optional["Engine"]:
        """The warm engine under ``key`` WITHOUT taking a lease — the
        audit plane's divergence probe runs through it (a replica is
        'warm on a signature' whether the program is bucket-leased or
        pool-idle). None when absent; the caller must tolerate a
        concurrent eviction (the freed engine's probe raises, which the
        probe paths already contain as 'unprobeable')."""
        with self._lock:
            ent = self._entries.get(key)
            return ent[0] if ent is not None else None

    def close(self) -> None:
        """Free every entry (frontend stop): after this, no pool engine
        holds device buffers — pinned by the conftest leak guard."""
        with self._lock:
            self.closed = True
            engines = [ent[0] for ent in self._entries.values()]
            self._entries.clear()
        for e in engines:
            e.free()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._entries),
                "capacity": self.capacity,
                "leased": sum(1 for ent in self._entries.values()
                              if ent[1] > 0),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


# ---------------------------------------------------------------------------
# Persistent compilation cache (AOT warm-start)
# ---------------------------------------------------------------------------

# Default on-disk cache location (gitignored). XLA keys entries by
# topology + program fingerprint, so one directory serves every
# (device topology, signature) pair without collisions.
DEFAULT_COMPILE_CACHE_DIR = ".jax_compile_cache"
DEFAULT_COMPILE_CACHE_BYTES = 512 * 1024 * 1024


def prune_compilation_cache(cache_dir: str,
                            max_bytes: int = DEFAULT_COMPILE_CACHE_BYTES,
                            ) -> int:
    """Bound the cache dir: delete oldest-mtime entries until the total
    is under ``max_bytes``. Returns files removed. Best-effort (a
    concurrent process may be writing)."""
    try:
        files = []
        for name in os.listdir(cache_dir):
            path = os.path.join(cache_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if os.path.isfile(path):
                files.append((st.st_mtime, st.st_size, path))
    except OSError:
        return 0
    total = sum(size for _, size, _ in files)
    removed = 0
    for _, size, path in sorted(files):
        if total <= max_bytes:
            break
        try:
            os.remove(path)
            removed += 1
            total -= size
        except OSError:
            pass
    return removed


def enable_compilation_cache(
    cache_dir: Optional[str] = None,
    max_bytes: int = DEFAULT_COMPILE_CACHE_BYTES,
) -> str:
    """Arm jax's persistent compilation cache for AOT warm-starts.

    A previously-seen signature's recompile (process restart, pool
    re-admission after eviction, a fleet replica respawn) becomes a
    cache deserialize instead of a fresh XLA compile — milliseconds, not
    seconds. The min-compile-time/min-entry-size gates are zeroed so
    CPU-cheap serving programs persist too (jax's defaults only persist
    compiles over ~1 s, which would exclude exactly the small mixed-
    workload signatures the multi-tenant frontend churns through). The
    directory is bounded by :func:`prune_compilation_cache` at arm time.
    Returns the directory used.
    """
    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or DEFAULT_COMPILE_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    prune_compilation_cache(cache_dir, max_bytes)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except AttributeError:
            pass  # older jax: the dir alone still caches big compiles
    return cache_dir
