"""Inner benchmark process — the half of ``bench.py`` that touches JAX.

``bench.py`` (repo root) never imports jax itself: backend init can hang or
die depending on how the TPU tunnel is feeling (round 1: the driver's run
failed with ``Unable to initialize backend 'axon'`` and a re-run hung with
no output). All device work therefore happens here, in a subprocess the
parent can bound with a timeout, retry, and fall back from.

Protocol: progress phases go to stderr (so a timeout post-mortem shows how
far we got); the result is ONE JSON line on stdout:

    {"backend": ..., "n_devices": N, "device_fps": ..., "ms_per_frame": ...,
     "e2e_fps": ..., "p50_ms": ..., "p99_ms": ...}

Measurement design is in dvf_tpu/benchmarks.py. The reference's own
measurement mechanisms are the FPS prints in webcam_app.py:88-95,152-163
and the trace stats in distributor.py:152-171; this reports the same two
quantities (throughput + delivered latency) for the TPU pipeline.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _log(msg: str) -> None:
    print(f"[bench-child +{time.perf_counter() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--height", type=int, default=1080)
    ap.add_argument("--width", type=int, default=1920)
    ap.add_argument("--frames", type=int, default=512, help="e2e streaming frames")
    ap.add_argument("--e2e-batch", type=int, default=16,
                    help="smaller batch for the latency half of the north star")
    ap.add_argument("--mode", choices=("headline", "device", "e2e"), default="headline")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (the CPU-fallback path passes "
                         "'cpu'). Env vars alone are not enough: a PJRT "
                         "sitecustomize can pin the TPU platform at "
                         "interpreter start, so we also flip jax.config "
                         "before any backend client exists.")
    args = ap.parse_args(argv)

    if args.platform:
        import os

        os.environ["JAX_PLATFORMS"] = args.platform
    _log("importing jax")
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    devices = jax.devices()
    backend = jax.default_backend()
    _log(f"backend={backend} n_devices={len(devices)} device0={devices[0]}")

    from dvf_tpu.benchmarks import bench_device_resident, bench_e2e_streaming
    from dvf_tpu.ops import get_filter

    filt = get_filter("invert")
    result: dict = {"backend": backend, "n_devices": len(devices)}

    if args.mode in ("headline", "device"):
        _log(f"device-resident: batch={args.batch} iters={args.iters} "
             f"{args.height}x{args.width}")
        r = bench_device_resident(filt, args.iters, args.batch, args.height, args.width)
        result.update(
            device_fps=round(r["fps"], 1),
            ms_per_batch=round(r["ms_per_batch"], 3),
            ms_per_frame=round(r["ms_per_frame"], 4),
            device_frames=r["frames"],
            device_wall_s=round(r["wall_s"], 2),
            h2d_mbps=round(r["h2d_mbps"], 1),
            batch=args.batch,
        )
        _log(f"device-resident done: {result['device_fps']} fps")

    if args.mode in ("headline", "e2e"):
        _log(f"e2e streaming: batch={args.e2e_batch} frames={args.frames}")
        r = bench_e2e_streaming(filt, args.frames, args.e2e_batch,
                                args.height, args.width)
        result.update(
            e2e_fps=round(r["fps"], 1),
            p50_ms=round(r["p50_ms"], 2),
            p99_ms=round(r["p99_ms"], 2),
            e2e_frames=r["frames"],
            e2e_wall_s=round(r["wall_s"], 2),
            e2e_batch=args.e2e_batch,
        )
        _log(f"e2e done: {result['e2e_fps']} fps p50={result['p50_ms']}ms")

    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
