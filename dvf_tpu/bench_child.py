"""Inner benchmark process — the half of ``bench.py`` that touches JAX.

``bench.py`` (repo root) never imports jax itself: backend init can hang or
die depending on how the TPU tunnel is feeling (round 1: the driver's run
failed with ``Unable to initialize backend 'axon'`` and a re-run hung with
no output; round 2: two 75 s probes were SIGKILLed). All device work
therefore happens here, in ONE subprocess the parent bounds with the full
bench budget — no separate probe process double-paying backend init.

Protocol: progress phases go to stderr with timestamps (so a timeout
post-mortem shows exactly how far init/compile got); the result is ONE
JSON line on stdout:

    {"backend": ..., "n_devices": N, "device_fps": ..., "ms_per_frame": ...,
     "h2d_mbps": ..., "d2h_mbps": ..., "link_roofline_fps": ...,
     "e2e_fps": ..., "roofline_frac": ..., "p50_ms": ..., "p99_ms": ...,
     "ingest": "streamed"|"monolithic", "overlap_efficiency": ...}

(``d2h_mbps`` times MATERIALIZED bytes — copy into a host destination
after block_until_ready — see benchmarks.bench_transfer; ``ingest`` /
``overlap_efficiency`` report the streamed shard-level transfer path and
how much H2D it hid under decode/compute, obs.metrics.IngestStats.)

Measurement design is in dvf_tpu/benchmarks.py. The reference's own
measurement mechanisms are the FPS prints in webcam_app.py:88-95,152-163
and the trace stats in distributor.py:152-171; this reports the same two
quantities (throughput + delivered latency) for the TPU pipeline, plus the
host↔device link roofline so a transfer-bound e2e number is attributed to
the link, not the framework.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Single source (package-side) for the persistent XLA compile-cache
# location; override with DVF_JAX_CACHE_DIR. benchtools.JAX_CACHE_DIR
# mirrors this for the jax-free repo-root scripts via the same env var.
JAX_CACHE_DIR = os.environ.get("DVF_JAX_CACHE_DIR", "/tmp/dvf_jaxcache")


def _log(msg: str) -> None:
    print(f"[bench-child +{time.perf_counter() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _heartbeat_during(label: str, period: float = 10.0):
    """Context manager: emit '<label> … still working' every ``period`` s.

    Backend init and first-compile are the phases that historically hang;
    the heartbeat turns a silent SIGKILL post-mortem into a timeline.
    """
    stop = threading.Event()

    def beat():
        n = 0
        while not stop.wait(period):
            n += 1
            _log(f"{label}… still working ({n * period:.0f}s)")

    t = threading.Thread(target=beat, daemon=True)

    class _Ctx:
        def __enter__(self):
            t.start()

        def __exit__(self, *exc):
            stop.set()
            t.join(timeout=1.0)

    return _Ctx()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--height", type=int, default=1080)
    ap.add_argument("--width", type=int, default=1920)
    ap.add_argument("--frames", type=int, default=512,
                    help="e2e frame cap; shrunk automatically when the "
                         "link roofline makes 512 frames exceed the budget")
    ap.add_argument("--e2e-batch", type=int, default=16)
    ap.add_argument("--lat-batch", type=int, default=4,
                    help="batch for the rate-controlled latency run (small "
                         "batches bound the assemble wait)")
    ap.add_argument("--e2e-budget-s", type=float, default=60.0,
                    help="target wall time for each e2e phase")
    ap.add_argument("--init-timeout", type=float, default=150.0,
                    help="give up on backend init after this many seconds "
                         "(healthy init is <5 s; a hung tunnel never "
                         "recovers within one bench window)")
    ap.add_argument("--collect-mode", choices=("thread", "inline"),
                    default="inline",
                    help="pipeline collect mode for the e2e phases; inline "
                         "measured ~12%% faster on CPU (151 vs 135 fps at "
                         "1080p) — one fewer thread on the GIL")
    ap.add_argument("--ingest", choices=("streamed", "monolithic"),
                    default="streamed",
                    help="e2e batch staging path: streamed overlaps "
                         "per-shard H2D with decode and the previous "
                         "batch's compute; monolithic is the classic "
                         "decode-all → one blocking device_put baseline")
    ap.add_argument("--ingest-depth", type=int, default=4,
                    help="streamed ingest: max shard transfers in flight")
    ap.add_argument("--egress", choices=("streamed", "monolithic"),
                    default="streamed",
                    help="e2e result fetch path: streamed issues per-"
                         "output-shard copy_to_host_async at submit and "
                         "materializes into preallocated slabs at collect; "
                         "monolithic is the classic whole-batch np.asarray "
                         "baseline")
    ap.add_argument("--transport", choices=("python", "ring"),
                    default="python",
                    help="e2e ingest transport; ring puts the native shm "
                         "ring (and with --wire, a codec) on the hot path")
    ap.add_argument("--wire", choices=("raw", "jpeg", "delta"),
                    default="raw",
                    help="e2e ring payload format — lets a BENCH round "
                         "A/B full-frame vs temporal-delta wire in the "
                         "same harness (delta's codec cost scales with "
                         "--motion's dirty ratio; wire/dirty-ratio "
                         "provenance lands in the result JSON)")
    ap.add_argument("--motion", choices=("roll", "block", "none"),
                    default="roll",
                    help="e2e synthetic stream motion: roll = full-motion "
                         "worst case, block = webcam-like low motion, "
                         "none = static")
    ap.add_argument("--mode", choices=("probe", "headline", "device", "e2e"),
                    default="headline")
    ap.add_argument("--no-decomp", action="store_true",
                    help="skip the per-stage latency decomposition in "
                         "headline mode")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (the CPU-fallback path passes "
                         "'cpu'). Env vars alone are not enough: a PJRT "
                         "sitecustomize can pin the TPU platform at "
                         "interpreter start, so we also flip jax.config "
                         "before any backend client exists.")
    args = ap.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    # Compile cache: a rerun (or the CPU fallback after a TPU bench that got
    # past compiling) skips compiles entirely.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE_DIR)
    _log("importing jax")
    import jax

    # config.update as well: `python -m dvf_tpu.bench_child` imports jax
    # via the package __init__ BEFORE main() runs, so the env default
    # above may already be snapshotted (same hazard cli._force_platform
    # documents).
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    # Init watchdog: a healthy backend initializes in <5 s (measured 0.1 s
    # on this tunnel); one that hasn't come up after --init-timeout never
    # will this window. The init call is uncancellable, so probe it from a
    # worker thread and hard-exit on timeout — rc=3 tells the parent to
    # fall back NOW instead of burning the whole bench budget.
    got: dict = {}

    def _init():
        try:
            got["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — init can throw UNAVAILABLE
            got["error"] = e

    t = threading.Thread(target=_init, daemon=True)
    with _heartbeat_during("backend init"):
        t.start()
        t.join(args.init_timeout)
    if "devices" not in got:
        if "error" in got:
            _log(f"backend init failed: {got['error']!r}")
        else:
            _log(f"backend init exceeded {args.init_timeout:.0f}s — "
                 f"tunnel is down, exiting for fast fallback")
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(3)
    devices = got["devices"]
    backend = jax.default_backend()
    _log(f"backend={backend} n_devices={len(devices)} device0={devices[0]}")

    if args.mode == "probe":
        # Pre-flight health check (VERDICT r3 item 3): backend init IS the
        # phase that hangs on a dead tunnel, so "init completed" is the
        # whole signal. A tiny computation confirms the chip executes.
        import jax.numpy as jnp

        val = float(jnp.arange(8).sum())
        print(json.dumps({"backend": backend, "n_devices": len(devices),
                          "device0": str(devices[0]), "probe_sum": val}),
              flush=True)
        return 0

    if args.platform is None and backend != "tpu":
        # jax silently landed on CPU (no TPU plugin claimed the chip).
        # Running the TPU-scale workload here would eat the parent's whole
        # bench budget before it could even label the result a fallback —
        # shrink to smoke scale now (the parent marks backend!="tpu" runs
        # as fallback either way).
        _log(f"backend is {backend!r}, not tpu — shrinking to smoke scale")
        args.iters = min(args.iters, 20)
        args.batch = min(args.batch, 8)
        args.frames = min(args.frames, 64)
        args.e2e_batch = min(args.e2e_batch, 8)
        args.e2e_budget_s = min(args.e2e_budget_s, 30.0)

    from dvf_tpu.benchmarks import (
        bench_device_resident,
        bench_e2e_latency,
        bench_e2e_streaming,
        bench_stage_decomposition,
        bench_transfer,
        roofline_fields,
    )
    from dvf_tpu.ops import get_filter

    filt = get_filter("invert")
    result: dict = {"backend": backend, "n_devices": len(devices)}

    if args.mode in ("headline", "device"):
        _log(f"device-resident: batch={args.batch} iters={args.iters} "
             f"{args.height}x{args.width}")
        with _heartbeat_during("device-resident (first run compiles)"):
            r = bench_device_resident(filt, args.iters, args.batch,
                                      args.height, args.width)
        result.update(
            device_fps=round(r["fps"], 1),
            ms_per_batch=round(r["ms_per_batch"], 3),
            ms_per_frame=round(r["ms_per_frame"], 4),
            device_frames=r["frames"],
            device_wall_s=round(r["wall_s"], 2),
            batch=args.batch,
        )
        result.update(roofline_fields(r, backend))
        _log(f"device-resident done: {result['device_fps']} fps "
             f"(roofline_frac={result.get('roofline_frac')})")

    if args.mode == "headline" and not args.no_decomp:
        # Per-stage latency decomposition at small batch: the compute leg
        # is tunnel-immune, so this is the measured core of the p50<10ms
        # budget (benchmarks/LATENCY.md); transfer legs are re-projected
        # with the link microbench below.
        _log("stage decomposition (batch 1/2/4)")
        with _heartbeat_during("stage decomposition"):
            decomp = bench_stage_decomposition(
                filt, sorted({1, 2, args.lat_batch}), args.height,
                args.width, reps=25 if backend == "tpu" else 5)
        # Codec provenance travels beside the encode_ms leg it produced
        # (backend/quality/threads — the satellite of VERDICT r5's
        # tunnel-independent CPU evidence).
        result["codec"] = decomp.pop("codec", None)
        result["stage_decomp_ms"] = decomp
        lat_key = f"batch_{args.lat_batch}"
        if lat_key in decomp:
            result["compute_p50_ms"] = decomp[lat_key]["compute_ms"]
        _log(f"decomposition done: {json.dumps(decomp)}")

    # Link microbench — also sizes the e2e phases: on a tunneled chip the
    # device→host link (~20 MB/s observed) caps 1080p delivery at a few
    # fps, and 512 frames would blow the whole budget.
    _log("transfer microbench")
    tr = bench_transfer(args.e2e_batch, args.height, args.width)
    frame_mb = tr["batch_mb"] / args.e2e_batch
    roof = 1.0 / (
        frame_mb / tr["h2d_mbps"]
        + frame_mb / tr["d2h_mbps"]
        + tr["d2h_fixed_ms"] / 1e3 / args.e2e_batch
    )
    result.update(
        h2d_mbps=round(tr["h2d_mbps"], 1),
        d2h_mbps=round(tr["d2h_mbps"], 1),
        link_roofline_fps=round(roof, 1),
    )
    _log(f"link: h2d={result['h2d_mbps']} MB/s d2h={result['d2h_mbps']} MB/s "
         f"→ roofline ≈ {result['link_roofline_fps']} fps at "
         f"{args.height}x{args.width}")

    if args.mode in ("headline", "e2e"):
        n_frames = max(48, min(args.frames, int(roof * args.e2e_budget_s)))
        _log(f"e2e throughput: batch={args.e2e_batch} frames={n_frames}")
        with _heartbeat_during("e2e throughput"):
            r = bench_e2e_streaming(filt, n_frames, args.e2e_batch,
                                    args.height, args.width,
                                    collect_mode=args.collect_mode,
                                    transport=args.transport,
                                    wire=args.wire,
                                    motion=args.motion,
                                    ingest=args.ingest,
                                    ingest_depth=args.ingest_depth,
                                    egress=args.egress)
        if "wire" in r:
            # Wire provenance + delta accounting (dirty ratio, keyframes,
            # resyncs): a --wire delta A/B row must say what it measured.
            result.update(transport=args.transport, wire=args.wire,
                          motion=args.motion, wire_stats=r["wire"])
        result.update(
            e2e_fps=round(r["fps"], 1),
            e2e_frames=r["frames"],
            e2e_wall_s=round(r["wall_s"], 2),
            e2e_batch=args.e2e_batch,
            collect_mode=args.collect_mode,
            # The transfer path the run actually took (streamed degrades
            # to monolithic on replicated shard layouts) and the fraction
            # of per-batch H2D cost it hid under decode/compute.
            ingest=r["ingest"],
            ingest_depth=r["ingest_depth"],
            overlap_efficiency=r["overlap_efficiency"],
            # The delivery-side mirror: the result-fetch path taken and
            # the fraction of blocking-D2H cost it hid (runtime/egress.py).
            egress=r["egress"],
            egress_overlap_efficiency=r["egress_overlap_efficiency"],
            # Per-kind contained-fault counters from the run (empty dict =
            # clean run) — a BENCH round asserts zero unexpected faults
            # before trusting the fps beside them.
            faults=r.get("faults", {}),
            recoveries=r.get("recoveries", 0),
            roofline_frac=round(r["fps"] / roof, 3) if roof else None,
        )
        _log(f"e2e done: {result['e2e_fps']} fps "
             f"({result['roofline_frac']} of link roofline, "
             f"ingest={result['ingest']} "
             f"overlap_eff={result['overlap_efficiency']})")

        # Rate-controlled latency: 0.8× measured throughput, queue ≈ batch —
        # p50 is transit, not queue depth (VERDICT r2 item 3).
        target = 0.8 * r["fps"]
        n_lat = max(32, min(args.frames, int(target * args.e2e_budget_s)))
        _log(f"e2e latency: batch={args.lat_batch} target={target:.1f} fps "
             f"frames={n_lat}")
        with _heartbeat_during("e2e latency"):
            rl = bench_e2e_latency(filt, n_lat, args.lat_batch,
                                   args.height, args.width, target,
                                   collect_mode=args.collect_mode,
                                   transport=args.transport,
                                   wire=args.wire,
                                   motion=args.motion,
                                   ingest=args.ingest,
                                   ingest_depth=args.ingest_depth,
                                   egress=args.egress)
        result.update(
            p50_ms=round(rl["p50_ms"], 2),
            p99_ms=round(rl["p99_ms"], 2),
            lat_frames=rl["frames"],
            lat_batch=args.lat_batch,
            lat_target_fps=round(rl["target_fps"], 1),
            lat_delivery_fps=round(rl["delivery_fps"], 2),
            lat_congested=rl["congested"],
            lat_backoffs=rl["backoffs"],
        )
        _log(f"latency done: p50={result['p50_ms']}ms p99={result['p99_ms']}ms "
             f"(target {result['lat_target_fps']} fps after "
             f"{rl['backoffs']} backoffs, congested={rl['congested']})")

    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
