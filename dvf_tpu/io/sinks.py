"""Frame sinks.

The reference's only sink is the pyglet side-by-side display
(webcam_app.py:118-164). The benchmark/default sink here is a null consumer
that measures what the reference prints ad hoc (draw FPS + buffer stats,
webcam_app.py:152-163): throughput and end-to-end latency percentiles.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from dvf_tpu.obs.metrics import LatencyStats


class NullSink:
    """Swallow frames; record per-frame end-to-end latency."""

    def __init__(self):
        self.stats = LatencyStats()

    @property
    def count(self) -> int:
        return self.stats.count

    def emit(self, index: int, frame: np.ndarray, capture_ts: float) -> None:
        self.stats.record(time.time() - capture_ts)

    def close(self) -> None:
        pass

    def fps(self) -> float:
        return self.stats.fps()

    def latency_percentiles(self, qs=(50, 90, 99)) -> dict:
        pct = self.stats.percentiles(qs)
        return {k.removesuffix("_ms"): v for k, v in pct.items()}


class CallbackSink:
    """Adapter: call a user function per delivered frame (display glue)."""

    def __init__(self, fn: Callable[[int, np.ndarray, float], None]):
        self.fn = fn
        self.count = 0

    def emit(self, index: int, frame: np.ndarray, capture_ts: float) -> None:
        self.count += 1
        self.fn(index, frame, capture_ts)

    def close(self) -> None:
        pass
