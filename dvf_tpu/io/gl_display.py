"""OpenGL texture-blit display sink — the reference's literal draw path.

webcam_app.py:118-150 renders the live and processed streams as two GL
textures blitted side by side (pyglet supplies the GL context + window;
the drawing itself is plain GL texture upload + quad blit). pyglet is not
installable here, but the GL path does not need it: this module creates a
**surfaceless EGL** context (Mesa, software rasterizer on a headless
host) and renders the same two-texture side-by-side composition into an
offscreen framebuffer — the identical GL call sequence the reference's
window receives (glTexImage2D upload, textured-quad blit per pane),
readable back for tests, recording, or piping to any presenter.

So the display layer has two interchangeable sinks:

- :class:`dvf_tpu.io.display.SideBySideSink` — cv2 window (interactive
  ESC handling); numpy composition.
- :class:`GLSideBySideSink` (here) — GL texture-blit composition,
  offscreen; the literal-parity path (``serve --display-backend gl``).

Both consume the same :class:`~dvf_tpu.io.display.LiveTap` and expose the
same emit/count/last_pane surface, so the pipeline does not care which
one it feeds.
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Callable, Optional

import numpy as np

from dvf_tpu.io.display import letterbox_geometry
from dvf_tpu.obs.metrics import RateLogger

# Mesa's surfaceless platform (EGL_PLATFORM_SURFACELESS_MESA): a context
# with no native windowing system at all — exactly right for a headless
# bench host. The llvmpipe software rasterizer draws on CPU.
_EGL_PLATFORM_SURFACELESS_MESA = 0x31DD


class GLUnavailable(RuntimeError):
    """Raised when no surfaceless EGL/GL stack can be initialized."""


class GLRenderer:
    """Owns one surfaceless EGL context + FBO; blits frame pairs.

    ``blit_pair(live, processed)`` uploads both RGB uint8 frames as GL
    textures, draws them as textured quads into the left/right halves of
    a (2*width, height) offscreen framebuffer (aspect-preserving
    letterbox for the live pane, like the cv2 sink), and returns the
    composed canvas read back from the GPU-side framebuffer — the
    reference's per-frame draw (webcam_app.py:118-150) minus the window.
    """

    def __init__(self, width: int, height: int):
        self.w, self.h = int(width), int(height)
        self.canvas_w = 2 * self.w
        os.environ.setdefault("PYOPENGL_PLATFORM", "egl")
        os.environ.setdefault("EGL_PLATFORM", "surfaceless")
        # Software rasterizer: deterministic and present on headless hosts.
        os.environ.setdefault("LIBGL_ALWAYS_SOFTWARE", "1")
        try:
            from OpenGL import EGL, GL  # noqa: N811
        except Exception as e:  # noqa: BLE001 — any import failure = no GL
            raise GLUnavailable(f"PyOpenGL/EGL import failed: {e!r}") from e
        self._EGL, self._GL = EGL, GL

        try:
            get_dpy = EGL.eglGetPlatformDisplayEXT
        except AttributeError as e:
            raise GLUnavailable("EGL_EXT_platform_base missing") from e
        self._dpy = get_dpy(_EGL_PLATFORM_SURFACELESS_MESA, None, None)
        major, minor = ctypes.c_long(), ctypes.c_long()
        if not EGL.eglInitialize(self._dpy, major, minor):
            raise GLUnavailable("eglInitialize failed (surfaceless Mesa)")
        # ADVICE r4: every failure past eglInitialize must eglTerminate
        # before re-raising — cmd_serve probes a throwaway GLRenderer on
        # every gl-backend start, and a partial GL stack (config/context/
        # makeCurrent/FBO failures) would otherwise leak one EGL display
        # per attempt.
        try:
            EGL.eglBindAPI(EGL.EGL_OPENGL_API)
            attribs = (ctypes.c_int * 5)(EGL.EGL_SURFACE_TYPE, 0,
                                         EGL.EGL_RENDERABLE_TYPE,
                                         EGL.EGL_OPENGL_BIT, EGL.EGL_NONE)
            cfgs = (EGL.EGLConfig * 1)()
            n = ctypes.c_long()
            if not EGL.eglChooseConfig(self._dpy, attribs, cfgs, 1, n) or not n.value:
                raise GLUnavailable("no EGL config for surfaceless OpenGL")
            self._ctx = EGL.eglCreateContext(self._dpy, cfgs[0],
                                             EGL.EGL_NO_CONTEXT, None)
            if not self._ctx:
                raise GLUnavailable("eglCreateContext failed")
            if not EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE,
                                      EGL.EGL_NO_SURFACE, self._ctx):
                raise GLUnavailable("eglMakeCurrent failed "
                                    "(EGL_KHR_surfaceless_context missing?)")

            # Two streaming textures (live, processed) + one FBO-attached
            # color texture as the composition canvas.
            self._tex = [GL.glGenTextures(1) for _ in range(2)]
            for t in self._tex:
                GL.glBindTexture(GL.GL_TEXTURE_2D, t)
                # LINEAR: the reference scales panes to the window; filtered
                # sampling is what a window blit does.
                GL.glTexParameteri(GL.GL_TEXTURE_2D, GL.GL_TEXTURE_MIN_FILTER,
                                   GL.GL_LINEAR)
                GL.glTexParameteri(GL.GL_TEXTURE_2D, GL.GL_TEXTURE_MAG_FILTER,
                                   GL.GL_LINEAR)
            self._fbo = GL.glGenFramebuffers(1)
            GL.glBindFramebuffer(GL.GL_FRAMEBUFFER, self._fbo)
            self._canvas_tex = GL.glGenTextures(1)
            GL.glBindTexture(GL.GL_TEXTURE_2D, self._canvas_tex)
            GL.glTexImage2D(GL.GL_TEXTURE_2D, 0, GL.GL_RGB, self.canvas_w,
                            self.h, 0, GL.GL_RGB, GL.GL_UNSIGNED_BYTE, None)
            GL.glFramebufferTexture2D(GL.GL_FRAMEBUFFER,
                                      GL.GL_COLOR_ATTACHMENT0,
                                      GL.GL_TEXTURE_2D, self._canvas_tex, 0)
            if (GL.glCheckFramebufferStatus(GL.GL_FRAMEBUFFER)
                    != GL.GL_FRAMEBUFFER_COMPLETE):
                raise GLUnavailable("offscreen framebuffer incomplete")
            GL.glEnable(GL.GL_TEXTURE_2D)
            # Release the context from the constructing thread: blit_pair
            # re-binds per call (the pipeline may construct on one thread
            # and deliver on another), and a context left current here
            # would make that bind fail with EGL_BAD_ACCESS.
            EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE,
                               EGL.EGL_NO_SURFACE, EGL.EGL_NO_CONTEXT)
        except Exception:
            try:
                EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE,
                                   EGL.EGL_NO_SURFACE, EGL.EGL_NO_CONTEXT)
                EGL.eglTerminate(self._dpy)
            except Exception:  # noqa: BLE001 — already failing; don't mask
                pass
            raise
        self._closed = False

    # ------------------------------------------------------------------

    def _upload(self, slot: int, frame: np.ndarray) -> None:
        GL = self._GL
        frame = np.ascontiguousarray(frame)
        GL.glBindTexture(GL.GL_TEXTURE_2D, self._tex[slot])
        # Rows are tightly packed uint8 RGB; width need not be 4-aligned.
        GL.glPixelStorei(GL.GL_UNPACK_ALIGNMENT, 1)
        GL.glTexImage2D(GL.GL_TEXTURE_2D, 0, GL.GL_RGB, frame.shape[1],
                        frame.shape[0], 0, GL.GL_RGB, GL.GL_UNSIGNED_BYTE,
                        frame)

    def _draw_pane(self, slot: int, x0: int, src_h: int, src_w: int) -> None:
        """Blit texture ``slot`` into the w×h pane at canvas x-offset
        ``x0``, aspect-preserving (letterboxed on the pane's black)."""
        GL = self._GL
        dh, dw = letterbox_geometry(src_h, src_w, self.h, self.w)
        # The viewport IS the letterbox: GL scales the full texture into
        # it with LINEAR sampling (what a window blit does).
        GL.glViewport(x0 + (self.w - dw) // 2, (self.h - dh) // 2, dw, dh)
        GL.glBindTexture(GL.GL_TEXTURE_2D, self._tex[slot])
        GL.glBegin(GL.GL_QUADS)
        # Texture row 0 is the image's TOP row, but GL's v=0 is the
        # framebuffer BOTTOM — flip v so the readback (row-flipped again)
        # returns image orientation.
        for u, v, x, y in ((0, 1, -1, -1), (1, 1, 1, -1),
                           (1, 0, 1, 1), (0, 0, -1, 1)):
            GL.glTexCoord2f(u, v)
            GL.glVertex2f(x, y)
        GL.glEnd()

    def blit_pair(self, live: Optional[np.ndarray],
                  processed: np.ndarray) -> np.ndarray:
        """Compose live | processed on the GL canvas; return it (H,2W,3).

        Safe from ANY (single) calling thread: an EGL context is
        thread-affine, and the pipeline delivers from the collect thread
        during the run but flushes the tail of the reorder buffer from
        the MAIN thread at end-of-stream — so the context is re-bound to
        the calling thread here and released on exit."""
        if self._closed:
            raise RuntimeError("GLRenderer is closed")
        EGL, GL = self._EGL, self._GL
        if not EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE,
                                  EGL.EGL_NO_SURFACE, self._ctx):
            raise RuntimeError("eglMakeCurrent failed in blit_pair")
        try:
            return self._blit_pair_bound(live, processed)
        finally:
            EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE,
                               EGL.EGL_NO_SURFACE, EGL.EGL_NO_CONTEXT)

    def _blit_pair_bound(self, live: Optional[np.ndarray],
                         processed: np.ndarray) -> np.ndarray:
        GL = self._GL
        GL.glBindFramebuffer(GL.GL_FRAMEBUFFER, self._fbo)
        GL.glViewport(0, 0, self.canvas_w, self.h)
        GL.glClearColor(0.0, 0.0, 0.0, 1.0)
        GL.glClear(GL.GL_COLOR_BUFFER_BIT)
        if live is not None:
            self._upload(0, live)
            self._draw_pane(0, 0, live.shape[0], live.shape[1])
        self._upload(1, processed)
        self._draw_pane(1, self.w, processed.shape[0], processed.shape[1])
        GL.glViewport(0, 0, self.canvas_w, self.h)
        # Tight rows on readback too: the default PACK alignment of 4
        # pads every row when 3*canvas_w is not 4-aligned (any odd
        # width), skewing or over-sizing the reshaped array.
        GL.glPixelStorei(GL.GL_PACK_ALIGNMENT, 1)
        out = GL.glReadPixels(0, 0, self.canvas_w, self.h, GL.GL_RGB,
                              GL.GL_UNSIGNED_BYTE)
        pane = np.frombuffer(out, np.uint8).reshape(self.h, self.canvas_w, 3)
        return pane[::-1].copy()  # GL rows are bottom-up

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        EGL = self._EGL
        EGL.eglMakeCurrent(self._dpy, EGL.EGL_NO_SURFACE, EGL.EGL_NO_SURFACE,
                           EGL.EGL_NO_CONTEXT)
        EGL.eglDestroyContext(self._dpy, self._ctx)
        EGL.eglTerminate(self._dpy)


class GLSideBySideSink:
    """GL-rendered live | processed sink (reference draw-path parity).

    Same surface as :class:`dvf_tpu.io.display.SideBySideSink` (emit/
    count/last_pane/stats_fn/telemetry) so serve can swap it in via
    ``--display-backend gl``; the composition runs through the GL
    texture-blit path instead of numpy/cv2. Offscreen by design — the
    composed canvas lands in ``last_pane`` (tests, recorders, external
    presenters)."""

    def __init__(
        self,
        live_tap: Any,
        stop_cb: Optional[Callable[[], None]] = None,
        stats_fn: Optional[Callable[[], dict]] = None,
        telemetry_interval_s: float = 5.0,
    ):
        self.live_tap = live_tap
        self.stop_cb = stop_cb
        self.stats_fn = stats_fn
        self.count = 0
        self.last_pane: Optional[np.ndarray] = None
        self._renderer: Optional[GLRenderer] = None
        self._telemetry = telemetry_interval_s > 0
        self._rate = RateLogger(
            "draw(gl)", telemetry_interval_s if self._telemetry else 5.0,
            quiet=True)

    def emit(self, index: int, processed: np.ndarray,
             capture_ts: float) -> None:
        self.count += 1
        if self._renderer is None:
            self._renderer = GLRenderer(processed.shape[1],
                                        processed.shape[0])
        self.last_pane = self._renderer.blit_pair(self.live_tap.latest,
                                                  processed)
        rate = self._rate.tick()
        if rate is not None and self._telemetry:
            import sys

            stats = self.stats_fn() if self.stats_fn else {}
            # Same brief subset as the cv2 sink — backends must not
            # change the telemetry shape.
            keys = ("buffered", "display_cursor", "latest_received",
                    "delivered", "dropped_at_ingest")
            brief = {k: stats[k] for k in keys if k in stats}
            print(f"[display:gl] {rate:.1f} fps {brief}",
                  file=sys.stderr, flush=True)

    def close(self) -> None:
        if self._renderer is not None:
            self._renderer.close()
            self._renderer = None
