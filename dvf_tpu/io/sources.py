"""Frame sources.

The reference's only source is a webcam capture thread
(webcam_app.py:67-116: cv2.VideoCapture at 1280x720@30, center-crop,
BGR→RGB). The framework generalizes the source into an iterator protocol and
adds the two SURVEY.md §4 test affordances the reference lacks: a synthetic
source (no camera) for benchmarks/integration tests and a file source.

A source yields ``(frame_u8, timestamp)``; ``None`` frame = end of stream.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional, Tuple

import numpy as np

Frame = Tuple[Optional[np.ndarray], float]


def _pace(next_t: float, period: float) -> float:
    """Sleep until ``next_t``; return the following due time.

    Drift-free on the normal path (the schedule advances by exactly one
    period, using the PRE-sleep clock — a post-sleep reading would
    accumulate sleep overshoot and systematically under-deliver at high
    rates) — but with no catch-up burst after a consumer stall
    (backpressure, jit warm-up): the next frame is due one full period
    after the LATER of the schedule and now, never immediately. Bursting
    to repay a stall would congest the very stream bench_e2e_latency is
    rate-controlling."""
    now = time.perf_counter()
    if now < next_t:
        time.sleep(next_t - now)
    return max(next_t, now) + period


class SyntheticSource:
    """Procedural moving-gradient frames — deterministic, camera-free.

    ``rate``: target frames/sec; 0 = unthrottled (benchmark mode, the
    analog of measuring pure pipeline capacity rather than the reference's
    30fps camera ceiling, webcam_app.py:14).

    ``motion`` selects the temporal structure, which is what the
    temporal-delta wire's dirty ratio is a function of:

    - ``True`` / ``"roll"`` — every pixel changes every frame (cyclic
      roll); the full-motion worst case (dirty ratio ≈ 1).
    - ``"block"`` — a small moving block over a STATIC background, the
      webcam-like low-motion workload (a subject moving against a fixed
      scene): per-frame change is ~2 block footprints, a few % of the
      frame, which is the regime the delta wire's order-of-magnitude
      codec saving is claimed for (benchmarks/DELTA_BENCH.json).
    - ``False`` / ``"none"`` — a fully static stream (dirty ratio 0;
      the bit-identity equivalence tests).
    """

    def __init__(
        self,
        height: int = 1080,
        width: int = 1920,
        channels: int = 3,
        n_frames: int = 300,
        rate: float = 0.0,
        seed: int = 0,
        motion: bool = True,
        texture: str = "noise",
    ):
        self.height, self.width, self.channels = height, width, channels
        self.n_frames = n_frames
        self.rate = rate
        self.motion = motion
        rng = np.random.default_rng(seed)
        # One textured base frame; per-frame variation is a cyclic roll +
        # brightness ramp. The rolls are PRE-COMPUTED (a small cycle of
        # distinct frames served round-robin as read-only views): an
        # unthrottled 1080p source doing a fresh 6 MB np.roll copy per frame
        # burns ~1 GB/s of host bandwidth + GIL inside the ingest thread and
        # becomes the pipeline bottleneck it exists to measure around.
        #
        # ``texture``: "noise" (default — iid noise + ramp; maximally
        # incompressible, the bench workload) or "structured" (gratings,
        # rings, and hard-edged blocks; spatially coherent content with
        # real edges — what super-resolution training needs, since iid
        # noise is information-destroyed by downscaling and unlearnable).
        if texture == "structured":
            yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
            rad = np.hypot(yy - height / 2.0, xx - width / 2.0)
            ch = [
                127.5 + 127.5 * np.sin(2 * np.pi * xx / 17.0),        # grating
                127.5 + 127.5 * np.sin(rad / 5.0),                    # rings
                ((xx // 11).astype(int) + (yy // 11).astype(int)) % 2 * 255.0,  # checker
            ]
            base = np.stack([ch[i % 3] for i in range(channels)], axis=-1)
            # hard-edged diagonal blocks for step edges in every channel
            block = (((xx + yy) // 23).astype(int) % 3 == 0)[..., None] * 60.0
            self._base = np.clip(base * 0.75 + block, 0, 255).astype(np.uint8)
        elif texture == "noise":
            base = rng.integers(0, 255, size=(height, width, channels), dtype=np.uint8)
            ramp = np.linspace(0, 255, width, dtype=np.uint8)[None, :, None]
            self._base = (base // 2 + ramp // 2).astype(np.uint8)
        else:
            raise ValueError(f"texture must be 'noise' or 'structured', got {texture!r}")
        if motion is True:
            motion = "roll"
        elif motion is False:
            motion = "none"
        if motion not in ("roll", "block", "none"):
            raise ValueError(
                f"motion must be 'roll', 'block', 'none' (or a bool), "
                f"got {motion!r}")
        self.motion = motion
        n_cycle = min(16, n_frames) if motion != "none" else 1
        if motion == "block":
            # Low-motion: invert a block (~1/6 of each linear dim → ~3%
            # of the area) walking a precomputed cycle of positions over
            # the static base. Same read-only-view serving discipline as
            # the roll cycle — the source must never become the
            # bottleneck it exists to measure around.
            bh, bw = max(8, height // 6), max(8, width // 6)
            self._cycle = []
            for i in range(n_cycle):
                f = self._base.copy()
                y0 = (i * max(1, (height - bh) // max(1, n_cycle - 1))
                      ) % max(1, height - bh + 1)
                x0 = (i * max(1, (width - bw) // max(1, n_cycle - 1))
                      ) % max(1, width - bw + 1)
                f[y0: y0 + bh, x0: x0 + bw] = 255 - f[y0: y0 + bh,
                                                      x0: x0 + bw]
                self._cycle.append(f)
        else:
            self._cycle = [
                np.roll(self._base, (i * 2) % self.width, axis=1)
                for i in range(n_cycle)
            ]
        for f in self._cycle:
            f.setflags(write=False)  # served as shared views — keep them immutable

    def __iter__(self) -> Iterator[Frame]:
        period = 1.0 / self.rate if self.rate > 0 else 0.0
        next_t = time.perf_counter()
        n_cycle = len(self._cycle)
        for i in range(self.n_frames):
            if period:
                next_t = _pace(next_t, period)
            yield self._cycle[i % n_cycle], time.time()
        yield None, time.time()


def center_square(frame: "np.ndarray", size: int) -> "np.ndarray":
    """Center-crop to ``size``² (the reference's crop, webcam_app.py:97-101),
    upscaling first when the frame is smaller than the target so any input
    geometry yields the fixed shape consumers like the ring transport need."""
    import cv2

    h, w = frame.shape[:2]
    if h < size or w < size:
        scale = max(size / h, size / w)
        frame = cv2.resize(
            frame, (int(np.ceil(w * scale)), int(np.ceil(h * scale))))
        h, w = frame.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return frame[top: top + size, left: left + size]


class VideoFileSource:
    """Decode a video file via cv2 (RGB uint8).

    ``target_size`` center-crops every frame to a fixed square — required
    for fixed-geometry consumers (``--transport ring``); None yields the
    file's native geometry.
    """

    def __init__(self, path: str, loop: bool = False, rate: float = 0.0,
                 target_size: Optional[int] = None):
        self.path = path
        self.loop = loop
        self.rate = rate
        self.target_size = target_size

    def __iter__(self) -> Iterator[Frame]:
        import cv2

        period = 1.0 / self.rate if self.rate > 0 else 0.0
        next_t = time.perf_counter()
        while True:
            cap = cv2.VideoCapture(self.path)
            ok, frame = cap.read()
            while ok:
                if period:
                    next_t = _pace(next_t, period)
                rgb = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
                if self.target_size:
                    rgb = center_square(rgb, self.target_size)
                yield rgb, time.time()
                ok, frame = cap.read()
            cap.release()
            if not self.loop:
                break
        yield None, time.time()


class WebcamSource:
    """Live webcam capture — the reference's source (webcam_app.py:67-116).

    Same settings: 1280x720@30 with a 1-frame driver buffer to minimize
    latency (webcam_app.py:69-75), optional center-crop to ``target_size``²
    (webcam_app.py:97-101), BGR→RGB (webcam_app.py:102).
    """

    def __init__(
        self,
        device: int = 0,
        capture_size: Tuple[int, int] = (1280, 720),
        fps: int = 30,
        target_size: Optional[int] = 512,
    ):
        self.device = device
        self.capture_size = capture_size
        self.fps = fps
        self.target_size = target_size

    def __iter__(self) -> Iterator[Frame]:
        import cv2

        cap = cv2.VideoCapture(self.device)
        cap.set(cv2.CAP_PROP_FRAME_WIDTH, self.capture_size[0])
        cap.set(cv2.CAP_PROP_FRAME_HEIGHT, self.capture_size[1])
        cap.set(cv2.CAP_PROP_FPS, self.fps)
        cap.set(cv2.CAP_PROP_BUFFERSIZE, 1)
        try:
            while True:
                ok, frame = cap.read()
                if not ok:
                    break
                if self.target_size:
                    # center_square also upscales when the camera ignores
                    # the capture-size request and delivers smaller frames
                    # — a naive negative-offset crop would emit wrong-shape
                    # frames and kill fixed-geometry consumers (ring).
                    frame = center_square(frame, self.target_size)
                yield cv2.cvtColor(frame, cv2.COLOR_BGR2RGB), time.time()
        finally:
            cap.release()
        yield None, time.time()


class ShmRingSource:
    """Consume frames that a SEPARATE PROCESS pushes into a POSIX
    shared-memory ring (`python -m dvf_tpu camera --shm NAME` is the
    producer) — the §2b 'camera process → framework process' path, with
    the C++ ring as the process boundary instead of the reference's ZMQ
    sockets. Drop-oldest freshness is enforced inside the ring by the
    producer's push.

    Wire format: raw uint8 frames of ``frame_shape``; a 1-byte payload is
    the end-of-stream sentinel (a real frame is always H·W·3 > 1 bytes).
    ``attach_timeout_s`` bounds waiting for the producer to create the
    ring; ``idle_timeout_s`` (None = forever) bounds waiting for the next
    frame once attached.
    """

    def __init__(
        self,
        shm_name: str,
        frame_shape: Tuple[int, int, int],
        attach_timeout_s: float = 10.0,
        idle_timeout_s: Optional[float] = 30.0,
        poll_s: float = 0.002,
    ):
        self.shm_name = shm_name
        self.frame_shape = tuple(frame_shape)
        self.attach_timeout_s = attach_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.poll_s = poll_s

    def __iter__(self) -> Iterator[Frame]:
        from dvf_tpu.transport.ring import FrameRing

        frame_bytes = int(np.prod(self.frame_shape))
        deadline = time.perf_counter() + self.attach_timeout_s
        ring = None
        while ring is None:
            try:
                # Pop buffer sized well beyond the expected frame so a
                # geometry mismatch surfaces as the explanatory ValueError
                # below, not as a 'raise max_frame_bytes' buffer error.
                ring = FrameRing(shm_name=self.shm_name, create=False,
                                 max_frame_bytes=max(4 * frame_bytes, 8 << 20))
            except OSError:
                if time.perf_counter() > deadline:
                    raise TimeoutError(
                        f"no producer created shm ring {self.shm_name!r} "
                        f"within {self.attach_timeout_s:.0f}s")
                time.sleep(0.05)
        try:
            idle_since = time.perf_counter()
            while True:
                rec = ring.pop()
                if rec is None:
                    if (self.idle_timeout_s is not None
                            and time.perf_counter() - idle_since > self.idle_timeout_s):
                        break  # producer stalled/died: end the stream
                    time.sleep(self.poll_s)
                    continue
                idle_since = time.perf_counter()
                payload, idx, ts = rec
                if len(payload) <= 1:
                    break  # EOF sentinel
                expected = int(np.prod(self.frame_shape))
                if len(payload) != expected:
                    # The two processes disagree about geometry — fail with
                    # the fix, not a reshape traceback. Square producers
                    # (webcam/file push --target-size²) are recognizable
                    # from the byte count.
                    s = int(round((len(payload) / 3) ** 0.5))
                    hint = (f" (producer frames look like a --target-size "
                            f"{s} square — pass --height {s} --width {s})"
                            if s * s * 3 == len(payload) else "")
                    raise ValueError(
                        f"shm producer pushed {len(payload)}-byte frames; "
                        f"this consumer expects {self.frame_shape} = "
                        f"{expected} bytes{hint}")
                yield (np.frombuffer(payload, np.uint8)
                       .reshape(self.frame_shape), ts)
        finally:
            ring.close()
        yield None, time.time()
