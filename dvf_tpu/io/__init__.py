from dvf_tpu.io.sources import SyntheticSource, VideoFileSource, WebcamSource  # noqa: F401
from dvf_tpu.io.sinks import CallbackSink, NullSink  # noqa: F401
