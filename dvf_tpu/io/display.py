"""Side-by-side live | processed display — the reference's product UX.

webcam_app.py:118-150 renders the live camera feed and the filtered stream
next to each other in one window (live left, processed right, 2×target
wide) and prints draw-FPS + buffer stats every 5 s (:152-163). This module
is that surface for the TPU pipeline:

- :class:`LiveTap` wraps any source and stashes the newest captured frame
  (the reference's ``self.frame_data`` hand-off between capture thread and
  draw loop, webcam_app.py:105-106,122-130 — here an explicit lock-free
  single-cell swap instead of a GIL-tolerated race, SURVEY.md §5.2);
- :class:`SideBySideSink` composes ``hstack(live, processed)`` per
  delivered frame, shows it via cv2 (`headless=True` skips the window for
  tests/CI), maps ESC to the pipeline's graceful stop
  (webcam_app.py:166-170), and prints the 5 s draw-FPS + stats line.

The processed pane lags the live pane by the pipeline's frame_delay — the
same visual behavior the reference's reorder buffer produces.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Iterator, Optional

import numpy as np

from dvf_tpu.obs.metrics import RateLogger


def letterbox_geometry(src_h: int, src_w: int, dst_h: int, dst_w: int):
    """Aspect-preserving fit of src into dst: ``(fit_h, fit_w)``, each ≥1.

    Shared by the cv2 and GL display backends so their panes scale
    identically."""
    scale = min(dst_h / src_h, dst_w / src_w)
    return (max(1, int(round(src_h * scale))),
            max(1, int(round(src_w * scale))))


class LiveTap:
    """Source wrapper: passes frames through, keeping the newest one."""

    def __init__(self, source: Any):
        self.source = source
        self.latest: Optional[np.ndarray] = None

    def __iter__(self) -> Iterator:
        for frame, ts in self.source:
            if frame is not None:
                self.latest = frame  # atomic ref swap under the GIL
            yield frame, ts


class SideBySideSink:
    """live | processed window (reference parity: webcam_app.py:118-164).

    ``stop_cb`` is called on ESC — wire it to ``Pipeline.stop`` for the
    reference's key-press shutdown (webcam_app.py:166-170). ``stats_fn``
    (e.g. ``pipeline.stats``) feeds the periodic print.
    """

    def __init__(
        self,
        live_tap: LiveTap,
        window: str = "dvf_tpu (live | processed)",
        stop_cb: Optional[Callable[[], None]] = None,
        stats_fn: Optional[Callable[[], dict]] = None,
        telemetry_interval_s: float = 5.0,
        headless: bool = False,
    ):
        self.live_tap = live_tap
        self.window = window
        self.stop_cb = stop_cb
        self.stats_fn = stats_fn
        self.headless = headless
        self.count = 0
        self.last_pane: Optional[np.ndarray] = None
        # interval <= 0 disables telemetry entirely (RateLogger with a 0
        # interval would fire on every tick, so give it a real interval
        # and gate the print instead).
        self._telemetry = telemetry_interval_s > 0
        self._rate = RateLogger(
            "draw", telemetry_interval_s if self._telemetry else 5.0, quiet=True
        )
        self._window_up = False

    # ------------------------------------------------------------------

    def _compose(self, processed: np.ndarray) -> np.ndarray:
        live = self.live_tap.latest
        if live is None:
            live = np.zeros_like(processed)
        if live.shape != processed.shape:
            # Letterbox the live feed into the processed geometry so the
            # panes always tile (the reference sidesteps this by using one
            # target_size for both, webcam_app.py:27-31): scale preserving
            # aspect, centered on a black canvas — never corner-crop, which
            # would misrepresent a larger live feed in the comparison.
            h, w = processed.shape[:2]
            sh, sw = letterbox_geometry(live.shape[0], live.shape[1], h, w)
            if (sh, sw) != live.shape[:2]:
                # Centered nearest-neighbor (sample at pixel centers, not
                # top-left corners — corner sampling never reads the last
                # row/col when downscaling); no cv2 dependency.
                ri = ((np.arange(sh) + 0.5) * live.shape[0] / sh).astype(np.intp)
                ci = ((np.arange(sw) + 0.5) * live.shape[1] / sw).astype(np.intp)
                live = live[np.minimum(ri, live.shape[0] - 1)][
                    :, np.minimum(ci, live.shape[1] - 1)]
            boxed = np.zeros_like(processed)
            y0, x0 = (h - sh) // 2, (w - sw) // 2
            boxed[y0:y0 + sh, x0:x0 + sw] = live
            live = boxed
        return np.hstack([live, processed])

    def emit(self, index: int, processed: np.ndarray, capture_ts: float) -> None:
        self.count += 1
        pane = self._compose(processed)
        self.last_pane = pane
        if not self.headless:
            import cv2

            cv2.imshow(self.window, cv2.cvtColor(pane, cv2.COLOR_RGB2BGR))
            self._window_up = True
            if cv2.waitKey(1) & 0xFF == 27 and self.stop_cb is not None:
                self.stop_cb()  # ESC → graceful stop (webcam_app.py:166-170)
        rate = self._rate.tick()
        if rate is not None and self._telemetry:
            stats = self.stats_fn() if self.stats_fn is not None else {}
            keys = ("buffered", "display_cursor", "latest_received",
                    "delivered", "dropped_at_ingest")
            brief = {k: stats[k] for k in keys if k in stats}
            print(f"[display] {rate:.1f} fps {brief}", file=sys.stderr, flush=True)

    def close(self) -> None:
        if self._window_up:
            import cv2

            cv2.destroyWindow(self.window)
            self._window_up = False
