"""Device & host memory accounting for the serving runtime.

Until this module nothing could answer "where is the memory": device
residency (compiled programs' state, live jax buffers) and the host
slab pools every streamed assembler/fetcher preallocates were both
invisible — a leak showed up as an OOM, never as a trend. This module
is the accounting layer:

- **device**: :func:`device_live_stats` walks ``jax.live_arrays()`` at
  scrape time (never on a hot path) — total live buffer bytes/count in
  this process; :func:`pool_device_stats` sums the PER-ENGINE resident
  state bytes of every live pool-managed program
  (``runtime.engine.live_pool_engines``) plus the freed-bytes counter
  ``Engine.free()`` maintains, so eviction/donation accounting is a
  counter, not a guess;
- **host**: the streamed ingest/egress modules register every live
  assembler/fetcher (`runtime.ingest.live_assemblers` /
  `runtime.egress.live_fetchers`); :func:`host_slab_stats` sums their
  occupied slab bytes. The conftest session-end guard asserts both go
  to ZERO when every frontend has closed — a pinned-slab leak fails
  the build instead of growing RSS forever;
- **gauges**: :func:`attach_memory_provider` registers one scrape-time
  provider emitting the ``dvf_mem_*`` family (global device walk, pool
  residency, per-owner host slabs, per-bucket rows when an owner
  exposes them);
- **trend**: :class:`LeakTrendWatch` — a tiny monotone-growth detector
  an owner feeds from its telemetry ring; a sustained strictly-rising
  byte count past the threshold trips the FlightRecorder ("the leak is
  young, dump the evidence now"), once per episode.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional

from dvf_tpu.obs.registry import GAUGE, COUNTER, MetricSample


def device_live_stats() -> Dict[str, Optional[float]]:
    """Process-wide live jax buffer accounting, walked at scrape time.

    ``jax.live_arrays()`` enumerates every live ``jax.Array`` this
    process holds (programs' donated/threaded state, in-flight batches,
    cached constants); summing ``nbytes`` gives the host-visible device
    residency. None values mean the walk is unavailable (no jax, exotic
    backend) — a gap, not a zero."""
    try:
        import jax

        arrs = jax.live_arrays()
    except Exception:  # noqa: BLE001 — accounting must never raise
        return {"device_live_bytes": None, "device_live_buffers": None}
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:  # noqa: BLE001 — a deleted-under-us array
            continue
    return {"device_live_bytes": float(total),
            "device_live_buffers": float(len(arrs))}


def pool_device_stats() -> Dict[str, float]:
    """Pool-managed program residency: per-engine measured state bytes
    (``Engine.state_bytes``, captured at compile) summed over the live
    registry, plus the monotone freed-bytes counter ``Engine.free()``
    advances — the eviction/donation accounting half."""
    from dvf_tpu.runtime.engine import (
        freed_device_bytes_total,
        live_pool_engines,
    )

    live = live_pool_engines()
    return {
        "pool_engines": float(len(live)),
        "pool_state_bytes": float(sum(
            getattr(e, "state_bytes", 0) or 0 for e in live)),
        "engine_freed_bytes_total": float(freed_device_bytes_total()),
    }


def host_slab_stats() -> Dict[str, float]:
    """Occupied host staging memory across every live streamed-ingest
    assembler and streamed-egress fetcher in the process (the
    registries in `runtime.ingest` / `runtime.egress`)."""
    from dvf_tpu.runtime.egress import live_fetchers
    from dvf_tpu.runtime.ingest import live_assemblers

    asm = [a for a in live_assemblers()]
    fet = [f for f in live_fetchers()]
    asm_bytes = sum(a.slab_bytes() for a in asm)
    fet_bytes = sum(f.slab_bytes() for f in fet)
    return {
        "host_slab_bytes": float(asm_bytes + fet_bytes),
        "host_ingest_slab_bytes": float(asm_bytes),
        "host_egress_slab_bytes": float(fet_bytes),
        "host_slab_owners": float(
            sum(1 for a in asm if a.slab_bytes())
            + sum(1 for f in fet if f.slab_bytes())),
    }


def memory_summary() -> Dict[str, Optional[float]]:
    """The flat ``stats()['memory']`` document: device walk + pool
    residency + host slabs, one dict."""
    out: Dict[str, Optional[float]] = {}
    out.update(device_live_stats())
    out.update(pool_device_stats())
    out.update(host_slab_stats())
    return out


def attach_memory_provider(
    registry,
    bucket_rows_fn: Optional[Callable[[], List[dict]]] = None,
) -> None:
    """Register the ``dvf_mem_*`` gauge family on ``registry``.

    All values are computed at scrape time (the device walk and slab
    sums never run on a serving path). ``bucket_rows_fn`` (optional)
    returns ``[{"bucket": label, "device_state_bytes": n,
    "host_slab_bytes": n}, ...]`` for per-bucket attribution — the
    serving frontend supplies one."""

    def provider() -> List[MetricSample]:
        out: List[MetricSample] = []
        for name, value in memory_summary().items():
            if value is None:
                continue
            kind = COUNTER if name.endswith("_total") else GAUGE
            out.append(MetricSample(f"mem_{name}", float(value), (), kind))
        if bucket_rows_fn is not None:
            for row in bucket_rows_fn():
                labels = (("bucket", str(row.get("bucket"))),)
                for key in ("device_state_bytes", "host_slab_bytes"):
                    v = row.get(key)
                    if v is not None:
                        out.append(MetricSample(
                            f"mem_bucket_{key}", float(v), labels, GAUGE))
        return out

    registry.register_provider(provider)


class LeakTrendWatch:
    """Monotone-growth detector over a periodically-sampled byte count.

    Feed it one ``observe(value)`` per telemetry sample. It trips when
    the last ``window`` samples are strictly increasing AND the total
    growth across them exceeds ``min_growth_bytes`` — a steady upward
    staircase, not noise around a plateau. One trip per episode: the
    watch re-arms only after a non-increasing sample.
    """

    def __init__(self, window: int = 8,
                 min_growth_bytes: float = 8 * 1024 * 1024):
        if window < 3:
            raise ValueError("leak-trend window must be >= 3")
        self.window = window
        self.min_growth_bytes = float(min_growth_bytes)
        self._values: "collections.deque[float]" = collections.deque(
            maxlen=window)
        self._tripped_episode = False
        self.trips_total = 0

    def observe(self, value: Optional[float]) -> Optional[str]:
        """Returns a trip reason string when this sample completes a
        leak trend, else None."""
        if value is None:
            return None
        v = float(value)
        if self._values and v <= self._values[-1]:
            # Plateau or shrink: the episode (if any) is over.
            self._tripped_episode = False
        self._values.append(v)
        if (len(self._values) < self.window or self._tripped_episode):
            return None
        vals = list(self._values)
        if any(b <= a for a, b in zip(vals, vals[1:])):
            return None
        growth = vals[-1] - vals[0]
        if growth < self.min_growth_bytes:
            return None
        self._tripped_episode = True
        self.trips_total += 1
        return (f"memory leak trend: {growth / 1e6:.1f} MB growth over "
                f"{self.window} consecutive rising samples "
                f"(now {vals[-1] / 1e6:.1f} MB)")
